"""`torrent-tpu top` — live terminal view of the pipeline ledger.

Polls a running bridge's ``GET /v1/pipeline`` (obs/ledger + obs/attrib)
and renders per-stage utilization bars, throughput, and the bottleneck
verdict, refreshing in place::

    torrent-tpu top — http://127.0.0.1:8421  wall 42.1s  pipeline 1.9 GiB/s
    stage    util                          busy      bytes       rate
    read     |#########                 |  31%     13.1s    80.0 GiB  6.1 GiB/s
    stage    |###                       |  11%      4.6s    80.0 GiB  17.4 GiB/s
    h2d      |##########################| 104%     43.8s     2.1 GiB  49.1 MiB/s
    ...
    bottleneck: h2d — 104% utilized, 49.1 MiB/s achieved vs 6.1 GiB/s demanded
    sched: 840 queued pieces (205.0 MiB), 312 launches, fill 0.94, 3 lanes
    autopilot: h2d limiting x4 [confirmed] — batch_target[sha1/262144] 16→64
      lane sha1/262144: target 64, deadline 80ms, backend device

When the bridge runs with ``--autopilot`` the frame also carries the
controller's last decision and every actuator's current value (the
``control`` key of ``/v1/pipeline``); ``--interval`` sets the refresh
cadence for watching the controller converge.

Utilization can exceed 100%: overlapped launches (depth-2 pipelining,
concurrent reader threads) accumulate more busy-seconds than wall
seconds — that is occupancy, not an error. ``--once`` prints a single
frame and exits (scripting/tests); the rendering is a pure function of
the JSON payload, so it is unit-testable without a bridge.

``--swarm`` switches to the wire-plane view: ``GET /v1/swarm`` (the
bridge, or the session MetricsServer — both answer it) rendered as the
per-peer scoreboard: top-K peers by transferred bytes with state flags,
pipeline depth, block-RTT p99, snub counters, and the overflow fold::

    torrent-tpu swarm — http://127.0.0.1:8421  3 peers (1 snubbed)  12.0 MiB down
    peer                      state  depth  blocks       down    rtt p99
    1a2b@10.0.0.2:6881        +Ci       16     512    8.0 MiB     3.9 ms
    ...
    (+41 more peers: 3.1 MiB down, 2 snubbed)
    announces: 12 ok / 3 failed (streak 3)

``--fleet`` switches to the fleet view: ``GET /v1/fleet`` (the bridge,
or a fabric worker's ``--obs-port`` server) rendered as the straggler
scoreboard plus the two-level bottleneck verdict::

    torrent-tpu fleet — http://127.0.0.1:8421  2/2 reporting  1.9 GiB/s
    pid status     units           rate   vs med  limits
    0   ok          3/3 done   49.1 MiB/s  0.05x  h2d        *straggler*
    1   ok          2/2 done    1.9 GiB/s  1.95x  launch
    fleet bottleneck: process 0 (h2d) — 96% utilized, 49.1 MiB/s ...
"""

from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request

from torrent_tpu.obs.attrib import format_rate as _fmt_rate

__all__ = [
    "fetch_fleet",
    "fetch_pipeline",
    "fetch_slo",
    "fetch_swarm",
    "fetch_timeline",
    "format_slo_line",
    "render_fleet",
    "render_history",
    "render_swarm",
    "render_top",
    "main",
]

BAR_WIDTH = 26
# sparkline glyphs for the --history rows (8 levels + a blank for zero)
SPARKS = " ▁▂▃▄▅▆▇█"
HISTORY_WIDTH = 60


def fetch_pipeline(url: str, timeout: float = 10.0) -> dict:
    """One ``GET /v1/pipeline`` read. Raises OSError-family on failure."""
    with urllib.request.urlopen(
        url.rstrip("/") + "/v1/pipeline", timeout=timeout
    ) as r:
        return json.loads(r.read().decode())


def fetch_fleet(url: str, timeout: float = 10.0) -> dict:
    """One ``GET /v1/fleet`` read. Raises OSError-family on failure."""
    with urllib.request.urlopen(
        url.rstrip("/") + "/v1/fleet", timeout=timeout
    ) as r:
        return json.loads(r.read().decode())


def fetch_timeline(url: str, timeout: float = 10.0) -> dict:
    """One ``GET /v1/timeline`` read. Raises OSError-family on failure."""
    with urllib.request.urlopen(
        url.rstrip("/") + "/v1/timeline", timeout=timeout
    ) as r:
        return json.loads(r.read().decode())


def fetch_swarm(url: str, timeout: float = 10.0) -> dict:
    """One ``GET /v1/swarm`` read. Raises OSError-family on failure."""
    with urllib.request.urlopen(
        url.rstrip("/") + "/v1/swarm", timeout=timeout
    ) as r:
        return json.loads(r.read().decode())


def fetch_slo(url: str, timeout: float = 10.0) -> dict | None:
    """One ``GET /v1/slo`` read; None when the route is unreachable."""
    try:
        with urllib.request.urlopen(
            url.rstrip("/") + "/v1/slo", timeout=timeout
        ) as r:
            return json.loads(r.read().decode())
    except (OSError, ValueError):
        return None


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.1f} {unit}"
    return f"{n} B"


def render_top(payload: dict, url: str = "") -> str:
    """Render one frame from a ``/v1/pipeline`` payload (pure)."""
    from torrent_tpu.obs.ledger import PIPELINE_STAGES

    rep = payload.get("attribution") or {}
    stages = rep.get("stages") or {}
    lines = []
    head = "torrent-tpu top"
    if url:
        head += f" — {url}"
    head += f"  wall {rep.get('wall_s', 0.0):.1f}s"
    if rep.get("pipeline_bps"):
        head += f"  pipeline {_fmt_rate(rep['pipeline_bps'])}"
    lines.append(head)
    if not stages:
        lines.append("pipeline idle: no stage activity recorded yet")
    else:
        lines.append(
            f"{'stage':8s} {'util':{BAR_WIDTH + 8}s} {'busy':>8s} "
            f"{'bytes':>10s} {'rate':>10s}"
        )
        order = [s for s in PIPELINE_STAGES if s in stages] + sorted(
            s for s in stages if s not in PIPELINE_STAGES
        )
        for name in order:
            st = stages[name]
            util = st.get("utilization", 0.0)
            fill = min(BAR_WIDTH, int(round(min(util, 1.0) * BAR_WIDTH)))
            bar = "#" * fill + " " * (BAR_WIDTH - fill)
            lines.append(
                f"{name:8s} |{bar}| {util * 100:4.0f}% {st.get('busy_s', 0.0):7.1f}s "
                f"{_fmt_bytes(st.get('bytes', 0)):>10s} "
                f"{_fmt_rate(st.get('achieved_bps')):>10s}"
            )
    ov = rep.get("overlap") or {}
    if ov.get("max_concurrent_stages", 0) or ov.get("busy_s", 0.0):
        # the double-buffering proof line: read while h2d while launch
        # shows up as wall seconds with ≥2 stages simultaneously busy
        lines.append(
            f"overlap: {ov.get('busy_s', 0.0):.1f}s with ≥2 stages busy "
            f"({ov.get('share', 0.0) * 100:.0f}% of wall, "
            f"max {ov.get('max_concurrent_stages', 0)} stages at once)"
        )
    bn = rep.get("bottleneck")
    if bn:
        line = (
            f"bottleneck: {bn['stage']} — {bn.get('utilization', 0) * 100:.0f}% "
            f"utilized, {_fmt_rate(bn.get('achieved_bps'))} achieved"
        )
        if bn.get("demanded_bps"):
            line += f" vs {_fmt_rate(bn['demanded_bps'])} demanded"
        if bn.get("headroom"):
            line += f" ({bn['headroom']}x headroom)"
        lines.append(line)
    sched = payload.get("sched") or {}
    if sched:
        lines.append(
            f"sched: {sched.get('queue_pieces', 0)} queued pieces "
            f"({_fmt_bytes(sched.get('queue_bytes', 0))}), "
            f"{sched.get('launches', 0)} launches, "
            f"fill {sched.get('mean_fill', 0.0):.2f}, "
            f"{sched.get('lanes', 0)} lanes"
        )
    ctl = payload.get("control")
    if ctl:
        # the autopilot's decision line: last verdict + what moved, plus
        # every actuator's current value (sched/control.decision_summary)
        from torrent_tpu.sched.control import decision_summary

        lines.append(decision_summary(ctl))
        for lane, st in sorted(((ctl.get("actuators") or {}).get("lanes") or {}).items()):
            lines.append(
                f"  lane {lane}: target {st.get('target')}, "
                f"deadline {st.get('deadline', 0) * 1000:.0f}ms, "
                f"backend {st.get('backend')}"
            )
    return "\n".join(lines)


def format_slo_line(name: str, obj: dict) -> str:
    """One objective's burn/budget summary line (pure) — shared by
    ``top --history`` and the ``replay`` CLI so the two never drift."""
    return (
        f"slo {name}: burn ×{obj.get('burn_rate', 0.0):.1f} "
        f"[{obj.get('classification', 'ok')}], budget "
        f"{obj.get('budget_remaining', 1.0) * 100:.1f}% left"
        + ("  ** BREACH **" if obj.get("breach") else "")
    )


def _spark(values: list[float], vmax: float | None = None) -> str:
    """One sparkline row (pure): values scaled into 9 glyph levels."""
    if not values:
        return ""
    top = vmax if vmax else max(values)
    if top <= 0:
        return SPARKS[0] * len(values)
    out = []
    for v in values:
        level = int(round(min(1.0, max(0.0, v / top)) * (len(SPARKS) - 1)))
        out.append(SPARKS[level])
    return "".join(out)


def render_history(timeline_payload: dict, slo_payload: dict | None = None,
                   url: str = "", width: int = HISTORY_WIDTH) -> str:
    """Render the ``--history`` frame from a ``/v1/timeline`` payload
    (pure): per-stage utilization sparklines over the ring's consecutive
    sample deltas, a pipeline-rate row, and (when ``/v1/slo`` answered
    with a report) the burn-rate/budget line per objective."""
    from torrent_tpu.obs.ledger import PIPELINE_STAGES
    from torrent_tpu.obs.timeline import replay_report

    rep = replay_report(timeline_payload)
    intervals = rep.get("intervals") or []
    intervals = intervals[-width:]
    lines = []
    head = "torrent-tpu history"
    if url:
        head += f" — {url}"
    head += f"  {rep.get('samples', 0)} samples over {rep.get('span_s', 0.0):.0f}s"
    if rep.get("drops"):
        head += f"  ({rep['drops']} dropped)"
    lines.append(head)
    if not intervals:
        lines.append("timeline empty: no sample intervals recorded yet")
        return "\n".join(lines)
    # one sparkline row per stage that ever held the limiting verdict:
    # utilization drawn only on the intervals that stage owned, so the
    # frame reads as "who owned each slice of the span"
    names = sorted({itv.get("limiting") for itv in intervals if itv.get("limiting")})
    order = [s for s in PIPELINE_STAGES if s in names] + [
        s for s in names if s not in PIPELINE_STAGES
    ]
    for name in order:
        series = [
            (itv.get("utilization") or 0.0) if itv.get("limiting") == name else 0.0
            for itv in intervals
        ]
        lines.append(f"{name:8s} |{_spark(series, vmax=1.0)}|  limiting intervals")
    rate = [itv.get("pipeline_bps") or 0.0 for itv in intervals]
    if any(rate):
        lines.append(
            f"{'rate':8s} |{_spark(rate)}|  peak {_fmt_rate(max(rate))}"
        )
    overall = (rep.get("overall") or {}).get("bottleneck")
    if overall:
        lines.append(
            f"overall: {overall['stage']} limited the span "
            f"({overall.get('utilization', 0) * 100:.0f}% utilized)"
        )
    report = (slo_payload or {}).get("report")
    for name, obj in sorted(((report or {}).get("objectives") or {}).items()):
        lines.append(format_slo_line(name, obj))
    return "\n".join(lines)


def _fmt_rtt(rtt: dict | None) -> str:
    """Human p99 RTT from a block_rtt summary (pure)."""
    rtt = rtt or {}
    if rtt.get("p99_overflow"):
        return ">64 s"
    p99 = rtt.get("p99_s")
    if p99 is None:
        return "—"
    if p99 >= 1.0:
        return f"{p99:.1f} s"
    return f"{p99 * 1e3:.1f} ms"


def render_swarm(payload: dict, url: str = "") -> str:
    """Render one swarm frame from a ``/v1/swarm`` payload (pure).

    The per-peer scoreboard: the snapshot's named top-K peers (already
    ranked by transferred bytes) with wire-state flags (``C`` = peer
    choking us, ``c`` = we choke it, ``I``/``i`` = interest each way,
    ``*`` = snubbed), live pipeline depth, block counts, bytes, and the
    block-RTT p99 upper bound — then the overflow fold and the announce
    health line."""
    counts = payload.get("counts") or {}
    totals = payload.get("totals") or {}
    peers = {
        k: v for k, v in (payload.get("peers") or {}).items()
        if isinstance(v, dict)
    }
    lines = []
    head = "torrent-tpu swarm"
    if url:
        head += f" — {url}"
    head += f"  {counts.get('connected', 0)} peers"
    if counts.get("snubbed"):
        head += f" ({counts['snubbed']} snubbed)"
    head += f"  {_fmt_bytes(totals.get('bytes_down', 0))} down"
    head += f" / {_fmt_bytes(totals.get('bytes_up', 0))} up"
    lines.append(head)
    if not peers:
        lines.append("swarm idle: no peer telemetry recorded yet")
    else:
        lines.append(
            f"{'peer':26s} {'state':6s} {'depth':>5s} {'blocks':>7s} "
            f"{'down':>10s} {'up':>10s} {'rtt p99':>9s}"
        )
        order = sorted(
            peers,
            key=lambda k: (
                -(peers[k].get("bytes_down", 0) + peers[k].get("bytes_up", 0)),
                k,
            ),
        )
        for key in order:
            p = peers[key]
            state = p.get("state") or {}
            flags = (
                ("C" if state.get("peer_choking") else "-")
                + ("c" if state.get("am_choking") else "-")
                + ("I" if state.get("peer_interested") else "-")
                + ("i" if state.get("am_interested") else "-")
                + ("*" if p.get("snubbed") else " ")
            )
            lines.append(
                f"{key[:26]:26s} {flags:6s} "
                f"{(p.get('pipeline') or {}).get('depth', 0):>5} "
                f"{p.get('blocks', 0):>7} "
                f"{_fmt_bytes(p.get('bytes_down', 0)):>10s} "
                f"{_fmt_bytes(p.get('bytes_up', 0)):>10s} "
                f"{_fmt_rtt(p.get('block_rtt')):>9s}"
            )
    overflow = payload.get("overflow")
    if isinstance(overflow, dict):
        lines.append(
            f"(+{overflow.get('peers', 0)} more peers: "
            f"{_fmt_bytes(overflow.get('bytes_down', 0))} down, "
            f"{overflow.get('snubbed', 0)} snubbed)"
        )
    lines.append(
        f"announces: {totals.get('announce_ok', 0)} ok / "
        f"{totals.get('announce_failed', 0)} failed"
        + (
            f" (streak {totals.get('announce_streak')})"
            if totals.get("announce_streak")
            else ""
        )
    )
    triggers = payload.get("triggers") or {}
    fired = ", ".join(f"{k}×{v}" for k, v in sorted(triggers.items()) if v)
    if fired:
        lines.append(f"flight triggers: {fired}")
    return "\n".join(lines)


def render_fleet(payload: dict, url: str = "") -> str:
    """Render one fleet frame from a ``/v1/fleet`` payload (pure).

    The straggler scoreboard (per-pid status, units, achieved rate vs
    the fleet median, limiting stage) plus the two-level bottleneck
    verdict: which PROCESS limits the fleet, and which STAGE inside it.
    """
    rows = [r for r in payload.get("scoreboard") or [] if isinstance(r, dict)]
    totals = payload.get("totals") or {}
    lines = []
    head = "torrent-tpu fleet"
    if url:
        head += f" — {url}"
    head += (
        f"  {payload.get('reporting', 0)}/{payload.get('nproc', 0)} reporting"
    )
    if totals.get("fleet_bps"):
        head += f"  fleet {_fmt_rate(totals['fleet_bps'])}"
    if payload.get("state"):
        head += f"  [{payload['state']}]"
    lines.append(head)
    if not rows:
        lines.append("fleet idle: no process digests held yet")
    else:
        lines.append(
            f"{'pid':>3s} {'status':10s} {'units':>14s} {'rate':>10s} "
            f"{'vs med':>7s}  limits"
        )
        for r in rows:
            units = f"{r.get('units_done', 0)}/{r.get('units_planned', 0)} done"
            if r.get("units_adopted"):
                units += f" +{r['units_adopted']}a"
            if r.get("adoption_debt"):
                units += f" (debt {r['adoption_debt']})"
            vs = r.get("vs_median")
            line = (
                f"{r.get('pid', 0):>3} {r.get('status', '?'):10s} "
                f"{units:>14s} {_fmt_rate(r.get('achieved_bps')):>10s} "
                f"{(f'{vs:.2f}x' if vs is not None else '—'):>7s}  "
                f"{r.get('limiting_stage') or '—'}"
            )
            if r.get("straggler"):
                line += "  *straggler*"
            lines.append(line)
    bn = payload.get("bottleneck")
    if bn:
        line = (
            f"fleet bottleneck: process {bn.get('pid')} "
            f"({bn.get('stage')}) — {bn.get('utilization', 0) * 100:.0f}% "
            f"utilized, {_fmt_rate(bn.get('achieved_bps'))} achieved"
        )
        if bn.get("fleet_median_bps"):
            line += f" vs fleet median {_fmt_rate(bn['fleet_median_bps'])}"
        if bn.get("headroom"):
            line += f" ({bn['headroom']}x headroom)"
        lines.append(line)
    slo = payload.get("slo")
    if isinstance(slo, dict):
        # fleet-wide budget health: the worst heartbeat-carried burn
        # rate (obs/slo digest_summary riding the PR 10 digests)
        line = (
            f"budget: worst burn ×{slo.get('worst_burn') or 0.0:.1f} "
            f"({slo.get('objective')}, pid {slo.get('pid')})"
        )
        if slo.get("breaching"):
            line += f"  ** {slo['breaching']} process(es) in BREACH **"
        lines.append(line)
    if payload.get("digest_drops"):
        lines.append(
            f"digest drops: {payload['digest_drops']} heartbeat(s) shed "
            "their obs digest (payload over the transport buffer)"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="torrent-tpu top", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--url", default="http://127.0.0.1:8421",
        help="bridge base URL (default %(default)s)",
    )
    ap.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh seconds (default %(default)s)",
    )
    ap.add_argument(
        "--once", action="store_true",
        help="print one frame and exit (no screen clearing)",
    )
    ap.add_argument(
        "--fleet", action="store_true",
        help="render the swarm-wide fleet view (GET /v1/fleet: straggler "
        "scoreboard + limiting process/stage) instead of the local "
        "pipeline ledger",
    )
    ap.add_argument(
        "--history", action="store_true",
        help="render the timeline view (GET /v1/timeline: per-stage "
        "sparkline rows over the sample ring + SLO burn/budget lines) "
        "instead of the instantaneous frame",
    )
    ap.add_argument(
        "--swarm", action="store_true",
        help="render the swarm wire-plane view (GET /v1/swarm: per-peer "
        "scoreboard — state flags, pipeline depth, block-RTT p99, "
        "snubs — plus the overflow fold and announce health) instead "
        "of the pipeline ledger",
    )
    args = ap.parse_args(argv)
    route = (
        "/v1/fleet" if args.fleet
        else "/v1/swarm" if args.swarm
        else "/v1/timeline" if args.history
        else "/v1/pipeline"
    )
    try:
        while True:
            try:
                payload = (
                    fetch_fleet(args.url) if args.fleet
                    else fetch_swarm(args.url) if args.swarm
                    else fetch_timeline(args.url) if args.history
                    else fetch_pipeline(args.url)
                )
            except (OSError, ValueError) as e:
                print(f"error: cannot reach {args.url}{route}: {e}",
                      file=sys.stderr)
                return 1
            frame = (
                render_fleet(payload, url=args.url) if args.fleet
                else render_swarm(payload, url=args.url) if args.swarm
                else render_history(payload, fetch_slo(args.url), url=args.url)
                if args.history
                else render_top(payload, url=args.url)
            )
            if args.once:
                print(frame)
                return 0
            # ANSI home+clear keeps the frame in place without curses
            sys.stdout.write("\x1b[H\x1b[2J" + frame + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":  # pragma: no cover - manual entrypoint
    sys.exit(main())
