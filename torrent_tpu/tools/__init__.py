from torrent_tpu.tools.make_torrent import make_torrent

__all__ = ["make_torrent"]
