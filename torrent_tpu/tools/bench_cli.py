"""`torrent-tpu bench` — unified bench rungs, banked-schema records, and
the trajectory comparator.

Replaces the ad-hoc ``.bench/*.sh`` rung logic with one command: every
rung is named, emits ONE banked-schema JSON line, and (for in-process
rungs) embeds the pipeline ledger's per-stage breakdown, so every
record carries its own bottleneck attribution instead of needing bench
archaeology. The moment a quiet device window opens, banking a rung is
one command.

Rungs::

    torrent-tpu bench smoke      # CPU-plane scheduler recheck (seconds;
                                 # the CI rung — in-process, ledger
                                 # breakdown embedded)
    torrent-tpu bench e2e        # end-to-end disk→slot→device recheck
                                 # through the zero-copy ingest path
                                 # (scheduler-fed, hasher selectable via
                                 # --hasher, ledger breakdown + overlap
                                 # embedded — the banked proof that
                                 # ingest wins are real, not anecdotal)
    torrent-tpu bench v2         # r6 sha256 leaf-plane rung: bench.py
                                 # BENCH_CONFIG=v2 under the median-of-3
                                 # contract, pallas backend (device)
    torrent-tpu bench flagship   # B=8192 headline shape re-confirmation
                                 # (device, BENCH_CONFIG=headline)
    torrent-tpu bench fabric     # r7 fabric scaling rung: 1/2/4-process
                                 # CPU fabric verify, median-of-3
    torrent-tpu bench controller # scheduler-autopilot A/B: the SAME
                                 # h2d-throttled recheck run with the
                                 # controller off then on; the record
                                 # banks both rates plus the decisions,
                                 # proving the observe→act loop beats
                                 # the static config (value = on-rate)
    torrent-tpu bench announce   # announce-plane rung: a many-client
                                 # announce storm (threads) against the
                                 # sharded swarm store, median-of-3;
                                 # the record embeds per-shard occupancy
                                 # and the announce latency summary, and
                                 # FAILS unless >= 4 shards were
                                 # exercised concurrently
    torrent-tpu bench swarm      # swarm wire-plane rung: a loopback
                                 # seed→leech download (real sockets,
                                 # real tracker, real picker/choke
                                 # economics), median-of-3 pieces/s;
                                 # the record embeds the swarm telemetry
                                 # snapshot (per-peer RTT/choke facts)
                                 # AND the recv-stage ledger breakdown,
                                 # so a swarm regression names the wire

``--smoke`` is an alias for the smoke rung (CI spells it that way).
Device rungs shell out to the repo's ``bench.py`` / ``.bench/
measure_fabric.py`` with the same env the retired rung scripts
exported, and pass the child's record through wrapped in the bench
schema; they obey bench.py's wedge-safety rules (never kill a
TPU-touching process).

Record schema (``"schema": "torrent-tpu-bench/1"``): the banked-record
fields bench.py already emits (metric/value/unit/vs_baseline/batch/
platform/…) plus ``rung``, ``measured_at_utc``, and ``ledger`` — the
per-stage busy/bytes/utilization table and the bottleneck verdict from
``obs/attrib.attribute`` (null for subprocess rungs, whose ledger lives
in the child). The fabric rung instead embeds ``per_process`` — each
worker's ledger/overlap breakdown per process count — and
``fleet_bottleneck``, worker 0's two-level fleet verdict (limiting
process → its limiting stage, ``obs/fleet``).

Comparator (``--compare``): gates a candidate record against the banked
trajectory (``BENCH_trajectory.json``, built by ``.bench/summarize.py
--trajectory`` and appended to by ``--bank``). Like-for-like means an
identical measurement shape — ``metric``, ``platform``, ``batch``,
payload shape (``piece_kb``/``bytes``), and host class (``nproc``) —
and the banked record is not flagged ``non_like_for_like`` (the
BENCH_CONFIGS_r05 shape caveats).
With no like-for-like banked record the comparator reports itself
**unarmed** and exits 0 — the CI gate arms itself only once a
comparable record is banked. ``--report-only`` never fails the run.

Exit codes: 0 = rung ok / comparator passed or unarmed; 1 = rung
failed, null value, or regression beyond ``--tolerance``; 2 = usage.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

__all__ = ["compare_record", "load_trajectory", "main"]

SCHEMA = "torrent-tpu-bench/1"
TRAJECTORY_SCHEMA = "torrent-tpu-bench-trajectory/1"
RUNGS = (
    "smoke", "e2e", "v2", "fabric", "flagship", "controller", "announce",
    "swarm", "scenario", "seed",
)
# the announce rung's acceptance floor: the banked rate must come from
# real cross-shard concurrency, not one hot shard
ANNOUNCE_MIN_SHARDS_HIT = 4
DEFAULT_TOLERANCE = 0.10
# the controller rung's deterministic throttle: every launch's h2d
# sleeps this long (sched/faults.py slow-interconnect model), so the
# autopilot's grown batches measurably amortize the fixed cost
CONTROLLER_FAULT = "latency_ms=25"

# env the retired .bench rung scripts exported, reproduced per rung
# (r6_sha256_rung.sh leg 2; the flagship shape from BENCH_CONFIGS_r05)
_DEVICE_RUNG_ENV = {
    "v2": {
        "BENCH_CONFIG": "v2",
        "BENCH_TOTAL_MB": "256",
        "BENCH_V2_NRES": "3",
        "BENCH_E2E_MB": "16",
        "BENCH_H2D_MB": "8",
        "BENCH_NO_REPLAY": "1",
        "TORRENT_TPU_SHA256_BACKEND": "pallas",
    },
    "flagship": {
        "BENCH_CONFIG": "headline",
        "BENCH_BATCH": "8192",
        "BENCH_TOTAL_MB": "2048",
        "BENCH_NO_REPLAY": "1",
    },
}


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _repo_root() -> str:
    """The source checkout root (bench.py / .bench live there). Device
    rungs need it; the smoke rung and comparator do not."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def default_trajectory_path() -> str:
    env = os.environ.get("TORRENT_TPU_BENCH_TRAJECTORY")
    if env:
        return env
    repo = os.path.join(_repo_root(), "BENCH_trajectory.json")
    if os.path.exists(repo):
        return repo
    return os.path.join(os.getcwd(), "BENCH_trajectory.json")


# ------------------------------------------------------------ smoke rung


def _build_smoke_torrent(tmp: str, total_mb: int, piece_kb: int):
    """Synthetic single-file torrent on real disk (the read stage must
    measure actual storage reads, not memory copies)."""
    import numpy as np

    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.storage.storage import FsStorage, Storage
    from torrent_tpu.tools.make_torrent import make_torrent

    payload_path = os.path.join(tmp, "bench_smoke.bin")
    rng = np.random.default_rng(7)
    total = total_mb << 20
    with open(payload_path, "wb") as f:
        f.write(rng.integers(0, 256, total, dtype=np.uint8).tobytes())
    meta = parse_metainfo(
        make_torrent(
            payload_path, "http://bench.invalid/announce",
            piece_length=piece_kb << 10,
        )
    )
    return Storage(FsStorage(tmp), meta.info), meta.info


async def _smoke(total_mb: int, piece_kb: int, batch_target: int) -> dict:
    """The CPU-plane rung: a scheduler-fed library recheck with the
    pipeline ledger attributing every stage. Deterministic, CPU-only,
    seconds — the rung CI runs on every PR."""
    from torrent_tpu.obs.attrib import attribute
    from torrent_tpu.obs.ledger import pipeline_ledger
    from torrent_tpu.obs.slo import default_objectives, evaluate_slo
    from torrent_tpu.obs.timeline import Timeline, TimelineSampler
    from torrent_tpu.parallel.bulk import verify_library_sched
    from torrent_tpu.sched import HashPlaneScheduler, SchedulerConfig

    with tempfile.TemporaryDirectory(prefix="tt_bench_smoke_") as tmp:
        storage, info = await asyncio.to_thread(
            _build_smoke_torrent, tmp, total_mb, piece_kb
        )
        led = pipeline_ledger()
        prev = led.snapshot()
        sched = HashPlaneScheduler(
            SchedulerConfig(batch_target=batch_target, flush_deadline=0.02),
            hasher="cpu",
        )
        await sched.start()
        # a private timeline bracketing the run (sampled manually, no
        # thread): the record embeds the ring facts + the SLO verdict
        # over them, so `summarize --trajectory` carries the schema
        timeline = Timeline(depth=16)
        sampler = TimelineSampler(timeline, scheduler=sched)
        try:
            sampler.sample_once()
            t0 = time.perf_counter()
            res = await verify_library_sched([(storage, info)], sched, tenant="bench")
            seconds = time.perf_counter() - t0
            sampler.sample_once()
            slo_rep = evaluate_slo(timeline.samples(), default_objectives())
        finally:
            await sched.close()
        rep = attribute(led.snapshot(), prev=prev)
    n_valid = int(res.bitfields[0].sum())
    pieces = info.num_pieces
    value = round(pieces / seconds, 1) if seconds > 0 else None
    return {
        "schema": SCHEMA,
        "rung": "smoke",
        "metric": f"sha1_recheck_smoke_{piece_kb}KiB_pieces_per_sec",
        "value": value if n_valid == pieces else None,
        "unit": "pieces/s",
        "pieces": pieces,
        "valid": n_valid,
        "bytes": info.length,
        "seconds": round(seconds, 4),
        "gib_per_sec": round(info.length / seconds / 2**30, 3) if seconds else None,
        "batch": batch_target,
        "piece_kb": piece_kb,
        "platform": "cpu",
        "plane": "cpu",
        # host class for the like-for-like key: a CPU-plane rate banked
        # on a big workstation must not gate a smaller CI runner
        "nproc": os.cpu_count(),
        "measured_at_utc": _utcnow(),
        "ledger": {
            "wall_s": rep["wall_s"],
            "stages": rep["stages"],
            "bottleneck": rep["bottleneck"],
            "overlap": rep.get("overlap"),
        },
        # the timeline/SLO plane's schema keys (PR 14): ring facts plus
        # the default-contract verdict over the bracketing samples — a
        # clean rung must show zero burn and no breach
        "timeline": {
            "samples": len(timeline.samples()),
            "drops": 0,
            "limiting": (rep.get("bottleneck") or {}).get("stage")
            if rep.get("bottleneck")
            else None,
        },
        "slo": {
            "worst": slo_rep.get("worst"),
            "breach_any": slo_rep.get("breach_any"),
            "objectives": {
                name: {
                    "burn_rate": obj.get("burn_rate"),
                    "budget_remaining": obj.get("budget_remaining"),
                    "classification": obj.get("classification"),
                }
                for name, obj in sorted(slo_rep.get("objectives", {}).items())
            },
        },
    }


async def _e2e(
    total_mb: int, piece_kb: int, batch_target: int, hasher: str
) -> dict:
    """The end-to-end ingest rung: a scheduler-fed recheck over real
    disk through the zero-copy path (disk → staging slot → device),
    with the ledger's per-stage breakdown AND the cross-stage overlap
    series embedded — read-while-h2d-while-launch is part of the banked
    record, so double-buffering regressions are visible, not anecdotal.
    ``hasher='tpu'`` runs the device plane (XLA-CPU off-device), 'cpu'
    the hashlib plane; both go through the same ingest path."""
    from torrent_tpu.obs.attrib import attribute
    from torrent_tpu.obs.ledger import pipeline_ledger
    from torrent_tpu.parallel.bulk import verify_library_sched
    from torrent_tpu.sched import HashPlaneScheduler, SchedulerConfig

    with tempfile.TemporaryDirectory(prefix="tt_bench_e2e_") as tmp:
        storage, info = await asyncio.to_thread(
            _build_smoke_torrent, tmp, total_mb, piece_kb
        )
        led = pipeline_ledger()
        prev = led.snapshot()
        sched = HashPlaneScheduler(
            SchedulerConfig(batch_target=batch_target, flush_deadline=0.02),
            hasher=hasher,
        )
        await sched.start()
        try:
            t0 = time.perf_counter()
            res = await verify_library_sched([(storage, info)], sched, tenant="bench")
            seconds = time.perf_counter() - t0
        finally:
            await sched.close()
        staging = sched.metrics_snapshot().get("staging", {})
        rep = attribute(led.snapshot(), prev=prev)
    n_valid = int(res.bitfields[0].sum())
    pieces = info.num_pieces
    value = round(pieces / seconds, 1) if seconds > 0 else None
    return {
        "schema": SCHEMA,
        "rung": "e2e",
        "metric": f"sha1_recheck_e2e_{hasher}_{piece_kb}KiB_pieces_per_sec",
        "value": value if n_valid == pieces else None,
        "unit": "pieces/s",
        "pieces": pieces,
        "valid": n_valid,
        "bytes": info.length,
        "seconds": round(seconds, 4),
        "gib_per_sec": round(info.length / seconds / 2**30, 3) if seconds else None,
        "batch": batch_target,
        "piece_kb": piece_kb,
        "platform": hasher,
        "plane": hasher,
        "nproc": os.cpu_count(),
        # zero-copy health facts alongside the rate: stage-copy bytes
        # must stay ~0 and every slab must have come back
        "staging_outstanding": staging.get("outstanding"),
        "staged_checkouts": staging.get("checkouts"),
        "measured_at_utc": _utcnow(),
        "ledger": {
            "wall_s": rep["wall_s"],
            "stages": rep["stages"],
            "bottleneck": rep["bottleneck"],
            "overlap": rep.get("overlap"),
        },
    }


async def _controller_ab(total_mb: int, piece_kb: int, batch_target: int) -> dict:
    """The scheduler-autopilot A/B rung: one shape, run twice under the
    same deterministic h2d throttle (:data:`CONTROLLER_FAULT`) — first
    with the static config, then with the autopilot armed. The fixed
    per-launch transfer cost means fewer, bigger launches win; the
    controller's batch actuator must discover that live, so
    controller-on ≥ controller-off pieces/s is the banked proof that
    the observe→act loop changes throughput instead of describing it."""
    from torrent_tpu.obs.attrib import attribute
    from torrent_tpu.obs.ledger import pipeline_ledger
    from torrent_tpu.parallel.bulk import verify_library_sched
    from torrent_tpu.sched import (
        ControlConfig,
        FaultPlan,
        HashPlaneScheduler,
        SchedulerAutopilot,
        SchedulerConfig,
    )

    with tempfile.TemporaryDirectory(prefix="tt_bench_ctl_") as tmp:
        storage, info = await asyncio.to_thread(
            _build_smoke_torrent, tmp, total_mb, piece_kb
        )

        async def run_once(controller_on: bool):
            led = pipeline_ledger()
            prev = led.snapshot()
            plan = FaultPlan.parse(CONTROLLER_FAULT)
            sched = HashPlaneScheduler(
                SchedulerConfig(
                    batch_target=batch_target,
                    flush_deadline=0.02,
                    plane_factory=plan.plane_factory(hasher="cpu"),
                ),
                hasher="cpu",
            )
            await sched.start()
            pilot = None
            if controller_on:
                pilot = SchedulerAutopilot(
                    sched,
                    ControlConfig(
                        enabled=True, interval_s=0.05,
                        hysteresis_ticks=1, cooldown_ticks=0,
                    ),
                ).start()
            try:
                t0 = time.perf_counter()
                res = await verify_library_sched(
                    [(storage, info)], sched, tenant="bench"
                )
                seconds = time.perf_counter() - t0
            finally:
                if pilot is not None:
                    await pilot.close()
                await sched.close()
            rep = attribute(led.snapshot(), prev=prev)
            status = pilot.status() if pilot is not None else None
            snap = sched.metrics_snapshot()
            return {
                "seconds": seconds,
                "valid": int(res.bitfields[0].sum()),
                "launches": snap.get("launches", 0),
                "lane_stats": snap.get("lane_stats", {}),
                "admission_factor": snap.get("admission_factor", 1.0),
                "rep": rep,
                "control": status,
            }

        off = await run_once(False)
        on = await run_once(True)
    pieces = info.num_pieces
    off_pps = round(pieces / off["seconds"], 1) if off["seconds"] > 0 else None
    on_pps = round(pieces / on["seconds"], 1) if on["seconds"] > 0 else None
    complete = off["valid"] == pieces and on["valid"] == pieces
    control = on["control"] or {}
    decision = control.get("decision") or {}
    rep = on["rep"]
    return {
        "schema": SCHEMA,
        "rung": "controller",
        "metric": f"sha1_recheck_controller_ab_{piece_kb}KiB_pieces_per_sec",
        # the headline value is the CONTROLLER-ON rate; the embedded A/B
        # record carries both sides so the win is auditable
        "value": on_pps if complete else None,
        "unit": "pieces/s",
        "pieces": pieces,
        "bytes": info.length,
        "batch": batch_target,
        "piece_kb": piece_kb,
        "platform": "cpu",
        "plane": "cpu",
        "nproc": os.cpu_count(),
        "fault": CONTROLLER_FAULT,
        "measured_at_utc": _utcnow(),
        "ab": {
            "controller_off_pps": off_pps,
            "controller_on_pps": on_pps,
            "ratio": (
                round(on_pps / off_pps, 3) if on_pps and off_pps else None
            ),
            "launches_off": off["launches"],
            "launches_on": on["launches"],
        },
        "decision": {
            "ticks": control.get("tick"),
            "bottleneck": (decision.get("bottleneck") or {}).get("stage"),
            "actions_total": control.get("actions_total"),
            "admission_factor": on["admission_factor"],
            "lane_targets": {
                lane: st.get("target")
                for lane, st in sorted(on["lane_stats"].items())
            },
        },
        "ledger": {
            "wall_s": rep["wall_s"],
            "stages": rep["stages"],
            "bottleneck": rep["bottleneck"],
            "overlap": rep.get("overlap"),
        },
    }


async def _announce_storm(
    clients: int, swarms: int, per_client: int, shards: int, numwant: int
) -> dict:
    """The announce-plane rung: ``clients`` worker threads storm the
    sharded swarm store concurrently, each announcing ``per_client``
    times round-robin across ``swarms`` distinct info-hashes (fixed
    sha1-derived hashes, so shard distribution is deterministic).
    Median-of-3 announces/s, with per-shard occupancy and a latency
    summary embedded — the banked proof that the control plane's O(1)
    sampling and leaf-locked shards actually scale, not a slogan.

    The record's value is ``None`` (rung FAILED) unless at least
    :data:`ANNOUNCE_MIN_SHARDS_HIT` shards held peers at the end — the
    rate must come from cross-shard concurrency."""
    import hashlib

    from torrent_tpu.net.types import AnnounceEvent
    from torrent_tpu.obs.hist import histograms
    from torrent_tpu.server.shard import ShardedSwarmStore

    info_hashes = [
        hashlib.sha1(f"bench-announce-swarm-{i}".encode()).digest()
        for i in range(swarms)
    ]

    def worker(store: ShardedSwarmStore, ci: int) -> list[float]:
        lats: list[float] = []
        for k in range(per_client):
            ih = info_hashes[(ci + k) % swarms]
            pid = b"%04d%04d" % (ci, k % 2000)
            pid = pid + b"p" * (20 - len(pid))
            t0 = time.perf_counter()
            store.announce(
                ih, pid, f"10.0.{ci % 256}.{k % 256}", 6881 + ci,
                left=(k % 4) and 1 or 0, event=AnnounceEvent.EMPTY,
                numwant=numwant,
            )
            lats.append(time.perf_counter() - t0)
        return lats

    rates: list[float] = []
    all_lats: list[float] = []
    snap: dict = {}
    for _rep in range(3):
        store = ShardedSwarmStore(n_shards=shards)
        t0 = time.perf_counter()
        lat_lists = await asyncio.gather(
            *(asyncio.to_thread(worker, store, ci) for ci in range(clients))
        )
        wall = time.perf_counter() - t0
        total = clients * per_client
        rates.append(total / wall if wall > 0 else 0.0)
        for lats in lat_lists:
            all_lats.extend(lats)
        snap = store.metrics_snapshot()
    # the storm observes into the shared log2 family too, so the rung
    # exercises the same wiring /metrics scrapes
    histograms().get(
        "torrent_tpu_tracker_announce_seconds",
        help="Tracker announce handle latency (receive to reply)",
        transport="storm",
    ).observe_batch(all_lats[-10000:])
    occupancy = {
        str(i): sh.get("peers", 0) for i, sh in enumerate(snap.get("shards", []))
    }
    shards_hit = sum(1 for v in occupancy.values() if v > 0)
    all_lats.sort()

    def _pct(q: float) -> float:
        return round(all_lats[int(q * (len(all_lats) - 1))] * 1e6, 1)

    value = round(statistics.median(rates), 1)
    ok = bool(all_lats) and shards_hit >= ANNOUNCE_MIN_SHARDS_HIT
    return {
        "schema": SCHEMA,
        "rung": "announce",
        "metric": f"tracker_announce_storm_{swarms}sw_announces_per_sec",
        "value": value if ok else None,
        "unit": "announces/s",
        "contract": "median-of-3",
        "rates": [round(r, 1) for r in rates],
        "announces": clients * per_client,
        "clients": clients,
        "swarms": swarms,
        "shards": shards,
        "shards_hit": shards_hit,
        "numwant": numwant,
        # the storm width is the launch shape for the like-for-like key
        "batch": clients,
        "platform": "cpu",
        "nproc": os.cpu_count(),
        "latency": {
            "p50_us": _pct(0.50) if all_lats else None,
            "p99_us": _pct(0.99) if all_lats else None,
            "max_us": _pct(1.0) if all_lats else None,
        },
        "shard_occupancy": occupancy,
        "store": {
            "swarms": snap.get("swarms"),
            "peers": snap.get("peers"),
            "numwant_clamped": snap.get("numwant_clamped"),
        },
        "measured_at_utc": _utcnow(),
        "ledger": None,  # the announce plane is not a pipeline-ledger path
    }


def _scenario_rung(occupancy: int, shards: int) -> dict:
    """The scenario rung: fill the sharded store to ``occupancy``
    single-seed swarms (distinct sha1-derived info-hashes, one peer
    each) on a virtual timeline, then run the bundled churn-storm
    scenario against that PRE-FILLED store — the banked rate is the
    wall-plane announces/s the serve stack sustains while holding
    million-swarm occupancy under live churn. The record's value is
    ``None`` unless the fill reached the requested occupancy, the SLO
    verdict passed, and the wall plane held its latency budget."""
    import hashlib
    import random

    from torrent_tpu.net.types import AnnounceEvent
    from torrent_tpu.scenario import VirtualClock, run_scenario
    from torrent_tpu.scenario.library import get
    from torrent_tpu.server.shard import ShardedSwarmStore

    spec = get("churn-storm")
    # same construction run_scenario uses for a fresh store: the engine
    # adopts the clock/rng, so the prefill and the scenario share one
    # coherent virtual timeline. churn-storm's short TTL means the
    # prefill population ages out by the final sweep, so the engine's
    # exact-occupancy oracle still balances.
    clock = VirtualClock(float(spec.peer_ttl_s) + 1.0)
    rng = random.Random(spec.seed)
    store = ShardedSwarmStore(
        n_shards=shards, peer_ttl=float(spec.peer_ttl_s),
        clock=clock, rng=rng,
    )

    chunk = 10_000
    t0 = time.perf_counter()
    for base in range(0, occupancy, chunk):
        batch = []
        for i in range(base, min(base + chunk, occupancy)):
            ih = hashlib.sha1(b"bench-scenario-swarm-%d" % i).digest()
            pid = b"-BN-" + ih[:16]
            batch.append((
                ih, pid,
                f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}",
                6881, 0, AnnounceEvent.STARTED, 0,
            ))
        store.announce_batch(batch)
    fill_wall = time.perf_counter() - t0
    fill_snap = store.metrics_snapshot()
    occupancy_held = fill_snap["peers"]

    result = run_scenario(spec, store=store)
    verdict = result["verdict"]
    wall = verdict["wall"]

    ok = (
        occupancy_held == occupancy
        and bool(verdict["pass"])
        and bool(wall["ok"])
    )
    return {
        "schema": SCHEMA,
        "rung": "scenario",
        "metric": f"scenario_churn_{occupancy}sw_announces_per_sec",
        "value": wall["announces_per_s"] if ok else None,
        "unit": "announces/s",
        "contract": "churn-storm verdict PASS at full occupancy",
        "scenario": spec.name,
        "seed": spec.seed,
        "ticks": spec.ticks,
        "population": verdict["population"],
        "occupancy": occupancy,
        "occupancy_held": occupancy_held,
        "fill_announces_per_sec": (
            round(occupancy / fill_wall, 1) if fill_wall > 0 else 0.0
        ),
        "shards": shards,
        "verdict_pass": bool(verdict["pass"]),
        "reasons": verdict["reasons"][:4],
        "budget": verdict["budget"],
        # the scenario population is the launch shape for the
        # like-for-like key
        "batch": verdict["population"],
        "platform": "cpu",
        "nproc": os.cpu_count(),
        "latency": {
            "p50_us": wall["p50_us"],
            "p99_us": wall["p99_us"],
            "max_us": wall["max_us"],
        },
        "measured_at_utc": _utcnow(),
        "ledger": None,  # scenario verdicts are not a pipeline-ledger path
    }


async def _swarm_rung(total_mb: int, piece_kb: int) -> dict:
    """The swarm wire-plane rung: a real two-client loopback download
    (in-memory tracker, TCP sockets, the full picker/choke/endgame
    stack), median-of-3 pieces/s. The record embeds the swarm telemetry
    snapshot's facts (block-RTT p99, choke transitions, endgame
    cancels) plus the recv-stage ledger breakdown bracketing the
    final rep — a swarm throughput regression banks WITH evidence of
    whether the wire, the picker, or verification moved. (Deliberately
    NOT built on doctor's ``_LoopbackSwarm`` scaffold: each rep times
    leech-add→completion and recreates the tracker, a rep-scoped shape
    the smoke harness doesn't need.)"""
    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.obs.attrib import attribute
    from torrent_tpu.obs.ledger import pipeline_ledger
    from torrent_tpu.obs.swarm import swarm_telemetry
    from torrent_tpu.server.in_memory import run_tracker
    from torrent_tpu.server.tracker import ServeOptions
    from torrent_tpu.session.client import Client, ClientConfig
    from torrent_tpu.tools.make_torrent import make_torrent

    import numpy as np

    rates: list[float] = []
    swarm_fact: dict = {}
    rep: dict = {}
    pieces = 0
    total = total_mb << 20
    with tempfile.TemporaryDirectory(prefix="tt_bench_swarm_") as tmp:
        sd = os.path.join(tmp, "seed")
        os.makedirs(sd)
        rng = np.random.default_rng(11)
        with open(os.path.join(sd, "swarm.bin"), "wb") as f:
            f.write(rng.integers(0, 256, total, dtype=np.uint8).tobytes())

        async def one_rep(i: int) -> float:
            nonlocal swarm_fact, rep, pieces
            led = pipeline_ledger()
            prev = led.snapshot()
            # the registry is process-global and cumulative: the facts
            # embedded in the record are THIS rep's delta, so they
            # reconcile with the record's own bytes/pieces (an
            # accumulated 3-rep total would read as a 3x mismatch)
            base_totals = swarm_telemetry().snapshot().get("totals") or {}
            server, _ = await run_tracker(
                ServeOptions(http_port=0, udp_port=None, interval=1)
            )
            ann = f"http://127.0.0.1:{server.http_port}/announce"
            meta = parse_metainfo(
                make_torrent(
                    os.path.join(sd, "swarm.bin"), ann,
                    piece_length=piece_kb << 10,
                )
            )
            ld = os.path.join(tmp, f"leech{i}")
            os.makedirs(ld)
            seed = Client(ClientConfig(port=0, enable_upnp=False, resume=False))
            leech = Client(ClientConfig(port=0, enable_upnp=False, resume=False))
            await seed.start()
            await leech.start()
            try:
                t1 = await seed.add(meta, sd)
                assert t1.bitfield.complete, "seed recheck failed"
                t0 = time.perf_counter()
                t2 = await leech.add(meta, ld)
                deadline = t0 + 300.0
                while not t2.bitfield.complete:
                    if time.perf_counter() > deadline:
                        raise RuntimeError("swarm rung download stalled")
                    await asyncio.sleep(0.02)
                seconds = time.perf_counter() - t0
                pieces = meta.info.num_pieces
                snap = swarm_telemetry().snapshot()
                totals = snap.get("totals") or {}
                peer_rtts = [
                    p.get("block_rtt") or {}
                    for p in (snap.get("peers") or {}).values()
                    if (p.get("block_rtt") or {}).get("count")
                ]

                def delta(key):
                    return (totals.get(key) or 0) - (base_totals.get(key) or 0)

                swarm_fact = {
                    # live peers are per-rep already (fresh clients);
                    # the RTT summary covers the live per-peer records
                    "peers": snap.get("counts", {}).get("connected"),
                    "blocks": delta("blocks"),
                    "bytes_down": delta("bytes_down"),
                    "snubs": delta("snubs"),
                    "endgame_cancels": delta("endgame_cancels"),
                    "block_rtt_p99_s": max(
                        (r.get("p99_s") or 0.0 for r in peer_rtts),
                        default=None,
                    ),
                }
            finally:
                await leech.close()
                await seed.close()
                server.close()
            rep = attribute(led.snapshot(), prev=prev)
            return pieces / seconds if seconds > 0 else 0.0

        for i in range(3):
            rates.append(await one_rep(i))
    value = round(statistics.median(rates), 1) if all(rates) else None
    return {
        "schema": SCHEMA,
        "rung": "swarm",
        "metric": f"swarm_loopback_{piece_kb}KiB_pieces_per_sec",
        "value": value,
        "unit": "pieces/s",
        "contract": "median-of-3",
        "rates": [round(r, 1) for r in rates],
        "pieces": pieces,
        "bytes": total,
        "piece_kb": piece_kb,
        "batch": None,
        "platform": "cpu",
        "plane": "cpu",
        "nproc": os.cpu_count(),
        "measured_at_utc": _utcnow(),
        # the wire plane's own evidence: swarm telemetry facts + the
        # recv-stage breakdown of the final rep
        "swarm": swarm_fact,
        "ledger": {
            "wall_s": rep.get("wall_s"),
            "stages": rep.get("stages"),
            "bottleneck": rep.get("bottleneck"),
            "overlap": rep.get("overlap"),
        },
    }


async def _seed_rung(total_mb: int, piece_kb: int, leechers: int) -> dict:
    """The seeder-plane rung: ONE seeding client serving ``leechers``
    concurrent raw-wire loopback leechers, each pulling the FULL payload
    (staggered piece order spreads the read offsets). Banks sustained
    upload MiB/s measured from the serve telemetry's ``bytes_up`` delta
    — the bytes the egress plane actually pushed, duplicates included —
    plus block service p50/p99 (request-send to Piece-receipt on the
    leecher side, so choke-rotation queueing is IN the tail) and the
    egress fallback matrix (sendfile/preadv/copy deltas): an upload
    regression banks WITH evidence of whether zero-copy disengaged, the
    reactor shed, or the choke rotation stalled.

    Leech protocol discipline: a choked BEP 3 peer's requests are
    silently dropped, and every drop is bracketed by a later Unchoke —
    so the loop re-arms its whole request window on each Unchoke and
    keeps the window under ``serve_queue_depth`` (no backpressure sheds
    of our own traffic, no re-request timers, no mid-frame read
    cancellation)."""
    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.net import protocol as proto
    from torrent_tpu.obs.attrib import attribute
    from torrent_tpu.obs.ledger import pipeline_ledger
    from torrent_tpu.serve_plane.telemetry import serve_telemetry
    from torrent_tpu.session.client import Client, ClientConfig
    from torrent_tpu.session.torrent import TorrentConfig
    from torrent_tpu.tools.make_torrent import make_torrent

    import numpy as np

    piece_len = piece_kb << 10
    block = 16384
    window = 32  # outstanding per leecher, < serve_queue_depth (64)
    total = total_mb << 20
    # fewer slots than leechers: the crowd must contend, so the banked
    # p99 includes real choke-rotation waits (the economics under test)
    slots = max(4, leechers // 8)
    with tempfile.TemporaryDirectory(prefix="tt_bench_seed_") as tmp:
        sd = os.path.join(tmp, "seed")
        os.makedirs(sd)
        rng = np.random.default_rng(17)
        payload = rng.integers(0, 256, total, dtype=np.uint8).tobytes()
        with open(os.path.join(sd, "seed.bin"), "wb") as f:
            f.write(payload)
        meta = parse_metainfo(
            make_torrent(
                os.path.join(sd, "seed.bin"), "http://127.0.0.1:1/announce",
                piece_length=piece_len,
            )
        )
        n_pieces = meta.info.num_pieces
        seed = Client(ClientConfig(
            port=0, enable_upnp=False, resume=False,
            torrent=TorrentConfig(
                max_peers=leechers + 8,
                choke_interval=0.25,
                unchoke_slots=slots,
            ),
        ))
        obs = serve_telemetry()
        base_tot = obs.snapshot().get("totals") or {}
        base_paths = {
            k: dict(v)
            for k, v in (obs.snapshot().get("paths") or {}).items()
        }
        led = pipeline_ledger()
        prev = led.snapshot()
        lat: list[float] = []
        writers: list = []
        await seed.start()
        try:
            t = await seed.add(meta, sd)
            assert t.bitfield.complete, "seed recheck failed"

            async def leech(i: int) -> None:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", seed.port
                )
                writers.append(writer)
                pid = (b"-BR0001-" + f"{i:012d}".encode())[:20]
                await proto.send_handshake(writer, meta.info_hash, pid)
                await proto.read_handshake_head(reader)
                await proto.read_handshake_peer_id(reader)
                await proto.send_message(writer, proto.Interested())
                need: dict[tuple[int, int], int] = {}
                for j in range(n_pieces):
                    p = (i * 7 + j) % n_pieces
                    plen = min(piece_len, total - p * piece_len)
                    for off in range(0, plen, block):
                        need[(p, off)] = min(block, plen - off)
                pending: dict[tuple[int, int], float] = {}

                async def pump() -> None:
                    now = time.perf_counter()
                    for (p, off), ln in need.items():
                        if len(pending) >= window:
                            break
                        if (p, off) not in pending:
                            pending[(p, off)] = now
                            await proto.send_message(
                                writer, proto.Request(p, off, ln)
                            )

                unchoked = False
                while need:
                    msg = await proto.read_message(reader)
                    if isinstance(msg, proto.Unchoke):
                        # everything in flight may have been shed by a
                        # choke tick — re-arm the whole window
                        unchoked = True
                        pending.clear()
                        await pump()
                    elif isinstance(msg, proto.Choke):
                        unchoked = False
                    elif isinstance(msg, proto.Piece):
                        key = (msg.index, msg.begin)
                        sent = pending.pop(key, None)
                        if sent is not None:
                            lat.append(time.perf_counter() - sent)
                        ln = need.pop(key, None)
                        if ln is not None:
                            base = msg.index * piece_len + msg.begin
                            if msg.block != payload[base:base + ln]:
                                raise RuntimeError(
                                    f"leecher {i}: block {key} diverges"
                                )
                        if unchoked:
                            await pump()

            t0 = time.perf_counter()
            try:
                await asyncio.wait_for(
                    asyncio.gather(*(leech(i) for i in range(leechers))), 600
                )
            except asyncio.TimeoutError:
                raise RuntimeError(
                    f"seed rung stalled ({leechers} leechers, "
                    f"{total_mb} MiB each)"
                ) from None
            wall = time.perf_counter() - t0
        finally:
            for w in writers:
                w.close()
            await seed.close()

    snap = obs.snapshot()
    tot = snap.get("totals") or {}

    def delta(key):
        return (tot.get(key) or 0) - (base_tot.get(key) or 0)

    paths = {
        k: {
            "blocks": v.get("blocks", 0)
            - (base_paths.get(k) or {}).get("blocks", 0),
            "bytes": v.get("bytes", 0)
            - (base_paths.get(k) or {}).get("bytes", 0),
        }
        for k, v in (snap.get("paths") or {}).items()
    }
    zero_copy = sum(
        paths.get(k, {}).get("blocks", 0) for k in ("sendfile", "preadv")
    )
    if zero_copy <= 0:
        raise RuntimeError(
            f"no zero-copy egress on a contiguous single-file layout "
            f"(fallback matrix: {paths})"
        )
    if delta("optimistic_rotations") <= 0:
        raise RuntimeError(
            f"optimistic slot never rotated over {leechers} leechers "
            f"vs {slots} slots"
        )
    lat.sort()
    rep = attribute(led.snapshot(), prev=prev)
    return {
        "schema": SCHEMA,
        "rung": "seed",
        "metric": f"seed_{leechers}leech_{piece_kb}KiB_upload_MiB_per_sec",
        "value": round(delta("bytes_up") / (1 << 20) / wall, 1)
        if wall > 0 else None,
        "unit": "MiB/s",
        "contract": "sustained, full payload per leecher, dupes counted",
        "leechers": leechers,
        "block_p50_ms": round(lat[len(lat) // 2] * 1e3, 2) if lat else None,
        "block_p99_ms": round(lat[int(0.99 * (len(lat) - 1))] * 1e3, 2)
        if lat else None,
        "blocks": delta("blocks"),
        "bytes": total * leechers,
        "bytes_up": delta("bytes_up"),
        "piece_kb": piece_kb,
        "batch": None,
        "platform": "cpu",
        "plane": "cpu",
        "nproc": os.cpu_count(),
        "measured_at_utc": _utcnow(),
        # the serve plane's own evidence: the egress fallback matrix +
        # reject/rotation counters bracketing the run
        "serve": {
            "paths": paths,
            "unchoke_slots": slots,
            "rounds": delta("rounds"),
            "optimistic_rotations": delta("optimistic_rotations"),
            "rejects_backpressure": delta("rejects_backpressure"),
            "rejects_choked": delta("rejects_choked"),
            "rejects_capacity": delta("rejects_capacity"),
            "rejects_per_ip": delta("rejects_per_ip"),
        },
        "ledger": {
            "wall_s": rep.get("wall_s"),
            "stages": rep.get("stages"),
            "bottleneck": rep.get("bottleneck"),
            "overlap": rep.get("overlap"),
        },
    }


# ----------------------------------------------------------- device rungs


def _run_bench_py(rung: str, timeout: float | None) -> dict:
    """Run the repo bench.py with the rung's env; pass its record
    through wrapped in the bench schema. Wedge safety is bench.py's own
    (never kills a TPU process; emits tpu_unavailable markers)."""
    bench_py = os.path.join(_repo_root(), "bench.py")
    if not os.path.exists(bench_py):
        raise FileNotFoundError(
            f"device rung {rung!r} needs the source checkout's bench.py "
            f"(looked at {bench_py})"
        )
    env = dict(os.environ)
    env.update(_DEVICE_RUNG_ENV[rung])
    proc = subprocess.run(
        [sys.executable, bench_py],
        env=env, cwd=_repo_root(), capture_output=True, text=True,
        timeout=timeout,
    )
    line = ""
    for out_line in (proc.stdout or "").splitlines():
        out_line = out_line.strip()
        if out_line.startswith("{"):
            line = out_line  # last JSON line wins (bench.py contract)
    if not line:
        raise RuntimeError(
            f"bench.py emitted no record (rc={proc.returncode}): "
            f"{(proc.stderr or '')[-500:]}"
        )
    rec = json.loads(line)
    rec.update(
        schema=SCHEMA, rung=rung, measured_at_utc=_utcnow(),
        # the ledger lives in the child process; only in-process rungs
        # embed the stage breakdown
        ledger=None,
    )
    return rec


def _run_fabric_rung(timeout: float | None) -> dict:
    """The r7 scaling rung: 1/2/4-process CPU fabric verify, median-of-3
    per process count, value = the 4-process GiB/s. The record embeds
    every leg's PER-PROCESS ledger/overlap breakdown (last rep) plus the
    fleet's two-level bottleneck verdict — the rate banks WITH its
    attribution, so a scaling regression names the process and stage
    that caused it instead of needing bench archaeology."""
    measure = os.path.join(_repo_root(), ".bench", "measure_fabric.py")
    if not os.path.exists(measure):
        raise FileNotFoundError(
            f"fabric rung needs the source checkout ({measure} missing)"
        )
    results: dict[int, list[float]] = {}
    per_process: dict[str, list] = {}
    fleet_bottleneck: dict[str, dict | None] = {}
    with tempfile.TemporaryDirectory(prefix="tt_bench_fabric_") as work:
        for nproc in (1, 2, 4):
            proc = subprocess.run(
                [
                    sys.executable, measure, "--workdir", work,
                    "--nproc", str(nproc), "--reps", "3",
                    "--torrents", "8", "--mb-per-torrent", "64",
                    "--hasher", os.environ.get("FABRIC_HASHER", "cpu"),
                ],
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
                capture_output=True, text=True, timeout=timeout,
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"fabric leg nproc={nproc} failed rc={proc.returncode}: "
                    f"{(proc.stderr or '')[-500:]}"
                )
            for out_line in (proc.stdout or "").splitlines():
                out_line = out_line.strip()
                if out_line.startswith("{"):
                    rec = json.loads(out_line)
                    results.setdefault(rec["nproc"], []).append(
                        rec["gib_per_sec"]
                    )
                    # last rep wins: one representative breakdown per leg
                    if rec.get("per_process"):
                        per_process[str(rec["nproc"])] = rec["per_process"]
                    if rec.get("fleet_bottleneck") is not None:
                        fleet_bottleneck[str(rec["nproc"])] = rec[
                            "fleet_bottleneck"
                        ]
    med = {n: round(statistics.median(v), 3) for n, v in sorted(results.items())}
    base = med.get(1)
    return {
        "schema": SCHEMA,
        "rung": "fabric",
        "metric": "fabric_scaling_gib_per_sec",
        "value": med.get(4),
        "unit": "GiB/s",
        "contract": "median-of-3",
        "scaling": {str(n): v for n, v in med.items()},
        "speedup_4p": round(med[4] / base, 2) if base and med.get(4) else None,
        "platform": os.environ.get("FABRIC_HASHER", "cpu"),
        "batch": None,
        "measured_at_utc": _utcnow(),
        # subprocess rung: the parent's own ledger stays null, but the
        # per-worker breakdowns (and the fleet verdict) ride along
        "ledger": None,
        "per_process": per_process,
        "fleet_bottleneck": fleet_bottleneck,
    }


# ------------------------------------------------------------- comparator


def load_trajectory(path: str) -> list[dict]:
    """Records list from a trajectory file (``{"records": [...]}``,
    a bare list, or a single record dict). Missing file → []."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    if isinstance(data, dict):
        recs = data.get("records")
        if isinstance(recs, list):
            return [r for r in recs if isinstance(r, dict)]
        return [data] if data.get("metric") else []
    if isinstance(data, list):
        return [r for r in data if isinstance(r, dict)]
    return []


# every field that defines a comparable measurement: the metric, the
# plane (platform), the launch shape (batch), the payload shape
# (piece_kb/bytes), and the host class (nproc — CPU-plane throughput
# scales with cores, and a workstation-banked record must not gate a
# smaller CI runner). Fields absent from BOTH records match vacuously,
# so device bench.py records (no piece_kb/nproc) keep their old key.
_LIKE_KEYS = ("metric", "platform", "batch", "piece_kb", "bytes", "nproc")


def like_for_like(records: list[dict], cand: dict) -> list[dict]:
    """Banked records the candidate may be gated against: identical
    measurement shape (:data:`_LIKE_KEYS`), value present, and not
    carrying a non-like-for-like shape caveat (the BENCH_CONFIGS_r05
    discipline)."""
    return [
        r
        for r in records
        if r.get("value") is not None
        and not r.get("non_like_for_like")
        and all(r.get(k) == cand.get(k) for k in _LIKE_KEYS)
    ]


def compare_record(
    cand: dict, records: list[dict], tolerance: float = DEFAULT_TOLERANCE
) -> tuple[int, str]:
    """(exit_code, message): 0 = within tolerance of the banked best or
    comparator unarmed (no like-for-like record); 1 = regression."""
    if cand.get("value") is None:
        return 1, "comparator: candidate record has no value (rung failed?)"
    eligible = like_for_like(records, cand)
    if not eligible:
        return 0, (
            f"comparator unarmed: no banked like-for-like record for "
            f"metric={cand.get('metric')!r} platform={cand.get('platform')!r} "
            f"batch={cand.get('batch')!r} (gate arms once one is banked)"
        )
    best = max(r["value"] for r in eligible)
    floor = best * (1.0 - tolerance)
    value = cand["value"]
    if value < floor:
        return 1, (
            f"REGRESSION: {cand['metric']} = {value} {cand.get('unit', '')} "
            f"< {floor:.1f} (banked best {best} − {tolerance:.0%} tolerance, "
            f"{len(eligible)} like-for-like record(s))"
        )
    verdict = "improves on" if value > best else "within tolerance of"
    return 0, (
        f"comparator ok: {cand['metric']} = {value} {cand.get('unit', '')} "
        f"{verdict} banked best {best}"
    )


def bank_record(cand: dict, path: str) -> None:
    """Append the record to the trajectory file (atomic write; creates
    the file with the trajectory schema when missing). History is kept —
    the comparator gates against the best like-for-like value."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {"schema": TRAJECTORY_SCHEMA, "records": []}
    if isinstance(data, list):
        data = {"schema": TRAJECTORY_SCHEMA, "records": data}
    data.setdefault("records", []).append(cand)
    data["banked_at_utc"] = _utcnow()
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


# -------------------------------------------------------------------- cli


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="torrent-tpu bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "rung", nargs="?", choices=RUNGS,
        help="named rung to run (smoke/e2e/v2/fabric/flagship/"
        "controller/announce/swarm/scenario/seed)",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="alias for the smoke rung (the CI spelling)",
    )
    ap.add_argument(
        "--mb", type=int, default=8,
        help="smoke/e2e rungs: payload MiB (default %(default)s)",
    )
    ap.add_argument(
        "--piece-kb", type=int, default=256,
        help="smoke/e2e rungs: piece size KiB (default %(default)s)",
    )
    ap.add_argument(
        "--batch-target", type=int, default=32,
        help="smoke/e2e rungs: scheduler pieces-per-launch target",
    )
    ap.add_argument(
        "--hasher", default="tpu", choices=("tpu", "cpu"),
        help="e2e rung: hash plane (default %(default)s; 'tpu' is XLA — "
        "on a CPU-only host it still exercises the device-plane path)",
    )
    ap.add_argument(
        "--clients", type=int, default=8,
        help="announce rung: concurrent announcer threads "
        "(default %(default)s)",
    )
    ap.add_argument(
        "--swarms", type=int, default=32,
        help="announce rung: distinct info-hashes stormed "
        "(default %(default)s)",
    )
    ap.add_argument(
        "--per-client", type=int, default=2000,
        help="announce rung: announces per client per rep "
        "(default %(default)s)",
    )
    ap.add_argument(
        "--shards", type=int, default=8,
        help="announce rung: store shard count (default %(default)s)",
    )
    ap.add_argument(
        "--numwant", type=int, default=30,
        help="announce rung: peers requested per announce "
        "(default %(default)s)",
    )
    ap.add_argument(
        "--leechers", type=int, default=64,
        help="seed rung: concurrent raw-wire loopback leechers "
        "(default %(default)s)",
    )
    ap.add_argument(
        "--occupancy", type=int, default=1_000_000,
        help="scenario rung: swarms pre-filled into the store before "
        "the churn-storm scenario runs (default %(default)s)",
    )
    ap.add_argument(
        "--timeout", type=float, default=None,
        help="device-rung subprocess timeout seconds (default: none)",
    )
    ap.add_argument("--out", default=None, help="also write the record here")
    ap.add_argument(
        "--record", default=None, metavar="FILE",
        help="skip the run; compare/bank this existing record instead",
    )
    ap.add_argument(
        "--compare", action="store_true",
        help="gate the record against the banked trajectory",
    )
    ap.add_argument(
        "--trajectory", default=None, metavar="FILE",
        help="trajectory file (default: TORRENT_TPU_BENCH_TRAJECTORY or "
        "BENCH_trajectory.json in the repo root / cwd)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=DEFAULT_TOLERANCE,
        help="allowed fractional regression vs the banked best "
        "(default %(default)s)",
    )
    ap.add_argument(
        "--report-only", action="store_true",
        help="comparator reports but never fails the run",
    )
    ap.add_argument(
        "--bank", action="store_true",
        help="append the record to the trajectory file (self-banking)",
    )
    args = ap.parse_args(argv)

    rung = args.rung
    if args.smoke:
        if rung not in (None, "smoke"):
            print("error: --smoke conflicts with an explicit rung",
                  file=sys.stderr)
            return 2
        rung = "smoke"
    if rung is None and args.record is None:
        print("error: name a rung (smoke/e2e/v2/fabric/flagship/controller/"
              "announce/swarm/scenario/seed) or pass --record FILE",
              file=sys.stderr)
        return 2
    if rung == "announce" and (
        args.shards < ANNOUNCE_MIN_SHARDS_HIT
        or args.swarms < ANNOUNCE_MIN_SHARDS_HIT
    ):
        # refuse upfront instead of running a storm guaranteed to fail
        # the >=4-shards acceptance floor with a misleading null-value
        # error at the end
        print(
            f"error: the announce rung's banked rate must come from "
            f">= {ANNOUNCE_MIN_SHARDS_HIT} concurrently exercised shards; "
            f"--shards and --swarms must both be >= "
            f"{ANNOUNCE_MIN_SHARDS_HIT} (got --shards {args.shards} "
            f"--swarms {args.swarms})",
            file=sys.stderr,
        )
        return 2

    if args.record is not None:
        try:
            with open(args.record) as f:
                record = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read record {args.record!r}: {e}",
                  file=sys.stderr)
            return 2
    else:
        try:
            if rung == "smoke":
                record = asyncio.run(
                    _smoke(args.mb, args.piece_kb, args.batch_target)
                )
            elif rung == "e2e":
                record = asyncio.run(
                    _e2e(args.mb, args.piece_kb, args.batch_target, args.hasher)
                )
            elif rung == "controller":
                record = asyncio.run(
                    _controller_ab(args.mb, args.piece_kb, args.batch_target)
                )
            elif rung == "announce":
                record = asyncio.run(
                    _announce_storm(
                        args.clients, args.swarms, args.per_client,
                        args.shards, args.numwant,
                    )
                )
            elif rung == "swarm":
                record = asyncio.run(_swarm_rung(args.mb, args.piece_kb))
            elif rung == "seed":
                record = asyncio.run(
                    _seed_rung(args.mb, args.piece_kb, args.leechers)
                )
            elif rung == "scenario":
                record = _scenario_rung(args.occupancy, args.shards)
            elif rung == "fabric":
                record = _run_fabric_rung(args.timeout)
            else:
                record = _run_bench_py(rung, args.timeout)
        except (RuntimeError, FileNotFoundError,
                subprocess.TimeoutExpired) as e:
            print(f"error: rung {rung!r} failed: {e}", file=sys.stderr)
            return 1
        line = json.dumps(record, sort_keys=True)
        print(line)
        if args.out:
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                f.write(line + "\n")
            os.replace(tmp, args.out)

    rc = 0
    if record.get("value") is None and not args.report_only:
        print("bench: record value is null (device unavailable or rung "
              "failed)", file=sys.stderr)
        rc = 1

    trajectory_path = args.trajectory or default_trajectory_path()
    if args.bank and record.get("value") is not None:
        bank_record(record, trajectory_path)
        print(f"banked into {trajectory_path}", file=sys.stderr)
    if args.compare:
        code, message = compare_record(
            record, load_trajectory(trajectory_path), args.tolerance
        )
        print(message, file=sys.stderr)
        if code and not args.report_only:
            rc = max(rc, code)
    return rc


if __name__ == "__main__":  # pragma: no cover - manual entrypoint
    sys.exit(main())
