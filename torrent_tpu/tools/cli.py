"""torrent-tpu — the proof-of-concept CLI (reference roadmap, README.md:36).

One multiplexed entry point over the whole framework::

    torrent-tpu info     FILE.torrent
    torrent-tpu make     PATH TRACKER [-o OUT] [--comment C] [--piece-length N] [--hasher cpu|tpu]
    torrent-tpu verify   FILE.torrent DIR [--hasher cpu|tpu] [--batch N]
    torrent-tpu download SOURCE DIR [--port P] [--hasher cpu|tpu] [--seed] [--no-resume] [--files I,J]
    torrent-tpu tracker  [--http-port P] [--udp-port P] [--interval S]
    torrent-tpu bridge   [--port P] [--hasher cpu|tpu] [--batch-target N]
                         [--flush-deadline-ms MS] [--max-queue-mb MB] [--tenant-max-mb MB]
                         [--dev --fault-plan SPEC]
    torrent-tpu fabric-verify TORRENTS_DIR DATA_ROOT
                         [--coordinator HOST:PORT --num-processes N --process-id I]
                         [--cpu-devices K] [--heartbeat-dir DIR] [--hasher cpu|tpu]
                         [--obs-port P] [--fault-plan SPEC]
    torrent-tpu top      [--url URL] [--interval S] [--once] [--fleet]
    torrent-tpu bench    [smoke|v2|fabric|flagship] [--compare] [--bank]
                         [--trajectory FILE] [--tolerance F] [--report-only]

``download`` accepts either a ``.torrent`` file or a ``magnet:?...`` URI
(BEP 9 metadata fetch). Also runnable as ``python -m torrent_tpu``.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys


def _parse_hostport(spec: str) -> "tuple[str, int] | None":
    """Parse ``HOST:PORT`` / ``[v6]:PORT``; None when the host is empty
    or the port is outside 1..65535 (the magnet/x.pe validity rules —
    emitting specs our own parser rejects helps nobody)."""
    host, _, port_s = spec.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        return None
    host = host.strip("[]")
    if not host or not 0 < port < 65536:
        return None
    return (host, port)


def _cmd_magnet(args) -> int:
    """Emit a magnet URI for a .torrent: btih and/or btmh topics (hybrids
    carry both), dn, the announce-list as tr= params, url-list webseeds
    as ws=, plus any --peer x.pe bootstrap addresses."""
    from torrent_tpu.codec.magnet import Magnet
    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.codec.metainfo_v2 import parse_metainfo_v2
    from torrent_tpu.net.multitracker import parse_announce_list

    try:
        with open(args.torrent, "rb") as f:
            data = f.read()
    except OSError as e:
        print(f"error: cannot read {args.torrent}: {e}", file=sys.stderr)
        return 1
    m1 = parse_metainfo(data)
    m2 = parse_metainfo_v2(data)
    if m1 is None and m2 is None:
        print("error: not a valid .torrent file", file=sys.stderr)
        return 1
    trackers: list[str] = []
    if not args.no_trackers:
        raw = (m1.raw if m1 is not None else m2.raw) or {}
        tiers = parse_announce_list(raw)
        seen = set()
        for tier in tiers or []:
            for t in tier:
                if t not in seen:
                    seen.add(t)
                    trackers.append(t)
        announce = m1.announce if m1 is not None else (m2.announce or "")
        if announce and announce not in seen:
            trackers.insert(0, announce)
    peers = []
    for spec in args.peer:
        addr = _parse_hostport(spec)
        if addr is None:
            print(f"error: bad --peer {spec!r}", file=sys.stderr)
            return 1
        peers.append(addr)
    from torrent_tpu.codec.metainfo import parse_url_list

    raw_top = m1.raw if m1 is not None else m2.raw
    magnet = Magnet(
        info_hash=m1.info_hash if m1 is not None else None,
        info_hash_v2=m2.info_hash_v2 if m2 is not None else None,
        display_name=(m1.info.name if m1 is not None else m2.info.name),
        trackers=tuple(trackers),
        peer_addrs=tuple(peers),
        # url-list lives at the top level for BOTH planes
        web_seeds=parse_url_list((raw_top or {}).get(b"url-list")),
    )
    print(magnet.to_uri())
    return 0


def _cmd_info(args) -> int:
    from torrent_tpu.codec.metainfo import parse_metainfo

    with open(args.torrent, "rb") as f:
        data = f.read()

    def print_signers() -> None:
        from torrent_tpu.codec import signing

        for name in signing.list_signers(data):
            if not signing.has_embedded_certificate(data, name):
                # BEP 35 allows out-of-band keys: unverifiable is not bad
                print(
                    f"signed by:    {name} (BEP 35, no embedded certificate"
                    f" — check with `sign --check {name} --pub KEY`)"
                )
                continue
            ok = signing.verify_torrent(data, name)
            print(
                f"signed by:    {name} (BEP 35, embedded key "
                f"{'verifies' if ok else 'DOES NOT verify'})"
            )

    m = parse_metainfo(data)
    if m is None:
        from torrent_tpu.codec.metainfo_v2 import parse_metainfo_v2

        v2 = parse_metainfo_v2(data)
        if v2 is not None:
            print(f"name:         {v2.info.name}  (BitTorrent v2)")
            print(f"info hash v2: {v2.info_hash_v2.hex()}")
            print(f"announce:     {v2.announce}")
            print(f"total size:   {v2.info.length:,} bytes")
            print(f"piece length: {v2.info.piece_length:,}")
            print(f"files:        {len(v2.info.files)}")
            for i, fe in enumerate(v2.info.files[:20]):
                print(f"  [{i}] {'/'.join(fe.path)}  ({fe.length:,} bytes)")
            if len(v2.info.files) > 20:
                print(f"  ... and {len(v2.info.files) - 20} more")
            from torrent_tpu.codec.metainfo import (
                parse_collections,
                parse_similar,
                parse_update_url,
            )

            raw = getattr(v2, "raw", {}) or {}
            if similar := parse_similar(raw):
                print(f"similar:      {len(similar)} torrents (BEP 38)")
                for h in similar[:5]:
                    print(f"  - {h.hex()}")
            if cols := parse_collections(raw):
                print(f"collections:  {', '.join(cols)} (BEP 38)")
            if upd := parse_update_url(raw):
                print(f"update url:   {upd} (BEP 39)")
            print_signers()
            return 0
        print("error: not a valid .torrent file", file=sys.stderr)
        return 1
    info = m.info
    print(f"name:         {info.name}")
    print(f"info hash:    {m.info_hash.hex()}")
    print(f"announce:     {m.announce}")
    print(f"total size:   {info.length:,} bytes")
    print(f"piece length: {info.piece_length:,}")
    print(f"pieces:       {info.num_pieces:,}")
    if m.raw.get(b"info", {}).get(b"private") == 1:
        print("private:      yes (BEP 27)")
    if m.web_seeds:
        print(f"web seeds:    {len(m.web_seeds)} (BEP 19)")
        for u in m.web_seeds[:5]:
            print(f"  - {u}")
    if m.http_seeds:
        print(f"http seeds:   {len(m.http_seeds)} (BEP 17)")
        for u in m.http_seeds[:5]:
            print(f"  - {u}")
    if m.similar:
        print(f"similar:      {len(m.similar)} torrents (BEP 38)")
        for h in m.similar[:5]:
            print(f"  - {h.hex()}")
    if m.collections:
        print(f"collections:  {', '.join(m.collections)} (BEP 38)")
    if m.update_url:
        print(f"update url:   {m.update_url} (BEP 39)")
    print_signers()
    if info.files is not None:
        pads = sum(1 for fe in info.files if getattr(fe, "pad", False))
        print(
            f"files:        {len(info.files) - pads}"
            + (f" (+{pads} BEP 47 pad files)" if pads else "")
        )
        # indices are the handles `download --files I,J` takes
        shown = 0
        for i, fe in enumerate(info.files):
            if getattr(fe, "pad", False):
                continue
            print(f"  [{i}] {'/'.join(fe.path)}  ({fe.length:,} bytes)")
            shown += 1
            if shown >= 20:
                break
        if len(info.files) - pads > 20:
            print(f"  ... and {len(info.files) - pads - 20} more")
    return 0


def _cmd_feed(args) -> int:
    return asyncio.run(_feed_loop(args))


async def _feed_loop(args) -> int:
    """BEP 36 subscription: poll the feed, add new entries, seed what
    completes — until interrupted (or once with --once)."""
    from torrent_tpu.session.client import Client, ClientConfig
    from torrent_tpu.tools.feed import FeedPoller

    # gate spec parses before anything is constructed: a typo'd key is a
    # deterministic usage error, never a partially-started session
    require_signed = None
    if getattr(args, "require_signed", None):
        require_signed = _parse_require_signed(args.require_signed)
        if require_signed is None:
            return 2

    config = ClientConfig(port=args.port)
    if args.proxy:
        config.proxy = args.proxy
    try:
        client = Client(config)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    poller = None

    def save_seen() -> None:
        # atomic replace: a crash mid-write must not truncate the
        # subscription memory (a lost --seen file re-adds the whole feed
        # history on the next run) — same pattern as FsResumeStore
        if args.seen and poller is not None:
            tmp = args.seen + ".tmp"
            with open(tmp, "w") as f:
                f.write("\n".join(sorted(poller.seen)) + "\n")
            os.replace(tmp, args.seen)

    # everything after construction lives under the finally: an
    # unreadable --seen file or a failed start must still close the
    # client (and report cleanly, not as a traceback)
    try:
        await client.start()
        seen: set[str] = set()
        if args.seen and os.path.exists(args.seen):
            with open(args.seen) as f:
                seen = {line.strip() for line in f if line.strip()}
        poller = FeedPoller(
            client,
            args.url,
            args.dir,
            interval=args.interval,
            seen=seen,
            require_signed=require_signed,
        )
        added = await poller.poll_once()
        save_seen()
        for t in added:
            print(f"added: {t.info.name} ({t.metainfo.info_hash.hex()[:16]}...)")
        if not added:
            print("no new entries")
        if args.once:
            return 0
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # pragma: no cover - non-unix
                pass
        print(f"polling {args.url} every {args.interval:.0f}s (ctrl-c to stop)")
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=args.interval)
            except asyncio.TimeoutError:
                pass
            if stop.is_set():
                break
            try:
                added = await poller.poll_once()
                save_seen()
                for t in added:
                    print(f"added: {t.info.name}")
            except Exception as e:
                print(f"poll failed (will retry): {e}", file=sys.stderr)
        return 0
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await client.close()


def _parse_require_signed(spec: str) -> tuple[str, bytes] | None:
    """``SIGNER=PUBHEX`` → (signer, 32-byte key), or None + stderr."""
    signer, _, pub_hex = spec.partition("=")
    try:
        pub = bytes.fromhex(pub_hex)
    except ValueError:
        pub = b""
    if len(pub) != 32 or not signer:
        print(
            "error: --require-signed wants SIGNER=PUBHEX (64 hex chars)",
            file=sys.stderr,
        )
        return None
    return signer, pub


def _cmd_update(args) -> int:
    """BEP 39 from the command line: fetch the update-url and write the
    successor verbatim (no session needed — just the poll)."""
    from torrent_tpu.codec.metainfo import Metainfo, parse_any_metainfo
    from torrent_tpu.session.client import fetch_update

    with open(args.torrent, "rb") as f:
        data = f.read()
    parsed = parse_any_metainfo(data)
    if parsed is None:
        print("error: not a valid .torrent file", file=sys.stderr)
        return 1
    meta = parsed[0]
    if not isinstance(meta, Metainfo):
        # pure v2: the session wrapper carries update_url + the
        # truncated-SHA-256 identity fetch_update compares against
        from torrent_tpu.session.v2 import v2_session_meta

        meta = v2_session_meta(meta)
    url = getattr(meta, "update_url", None)
    if not url:
        print("no update-url in this torrent (BEP 39 key absent)")
        return 1
    proxy = None
    if args.proxy:
        from torrent_tpu.net.socks import ProxySpec

        try:
            proxy = ProxySpec.parse(args.proxy)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    # validate the gate spec BEFORE the fetch: a typo'd key must fail
    # deterministically, not lie dormant until the first real update
    req = getattr(args, "require_signed", None)
    parsed_req = None
    if req:
        parsed_req = _parse_require_signed(req)
        if parsed_req is None:
            return 2
    raw_out: list = []
    try:
        new_meta = asyncio.run(
            fetch_update(meta, proxy=proxy, raw_bytes_out=raw_out)
        )
    except Exception as e:
        print(f"error: update fetch failed: {e}", file=sys.stderr)
        return 1
    if new_meta is None:
        print(f"current: {url} serves the same torrent")
        return 0
    name = getattr(getattr(new_meta, "info", None), "name", "updated")
    if parsed_req is not None:
        # BEP 39 + BEP 35: a secure publishing pipeline. The SUCCESSOR
        # must carry a valid signature under the trusted key — an
        # update-url takeover cannot push an unsigned replacement.
        from torrent_tpu.codec import signing

        try:
            signing.ensure_signed(raw_out[0], *parsed_req)
        except ValueError as e:
            print(f"error: refusing update from {url}: {e}", file=sys.stderr)
            return 2
    if args.check:
        print(f"update available: {name!r} at {url}")
        return 0
    base = (
        args.torrent[: -len(".torrent")]
        if args.torrent.endswith(".torrent")
        else args.torrent
    )
    out = args.output or (base + ".updated.torrent")
    with open(out, "wb") as f:
        f.write(raw_out[0])
    print(f"update available: wrote {out} ({len(raw_out[0]):,} bytes)")
    return 0


def _cmd_make(args) -> int:
    similar = _parse_similar_args(args)
    if similar is None:
        return 2
    if args.v2 or args.hybrid:
        if getattr(args, "pad_files", False):
            # hybrid authoring piece-aligns on its own; pure v2 has no
            # pad concept — a silently ignored flag would mislead
            print(
                "note: --pad-files applies to v1 authoring only (v2/hybrid "
                "are piece-aligned by construction); ignoring",
                file=sys.stderr,
            )
        return _make_v2(args)
    from torrent_tpu.tools.make_torrent import make_torrent

    def progress(n):
        print(f"\rhashed {n} pieces", end="", file=sys.stderr, flush=True)
    data = make_torrent(
        args.path,
        args.tracker,
        comment=args.comment,
        piece_length=args.piece_length,
        hasher=args.hasher,
        progress=progress,
        announce_list=[[t] for t in args.also_tracker] or None,
        private=args.private,
        web_seeds=args.web_seed or None,
        pad_files=getattr(args, "pad_files", False),
        similar=similar or None,
        collections=args.collection or None,
        update_url=args.update_url,
    )
    print("", file=sys.stderr)
    out = args.output or (args.path.rstrip("/").rsplit("/", 1)[-1] + ".torrent")
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {out} ({len(data):,} bytes)")
    return 0


def _make_v2(args) -> int:
    """Author a pure-v2 (BEP 52) torrent: SHA-256 merkle file tree.

    File contents are passed as filesystem paths so hashing streams in
    bounded chunks — authoring a 60 GiB directory holds ~64 MiB resident.
    """
    import os

    from torrent_tpu.codec.metainfo_v2 import encode_metainfo_v2
    from torrent_tpu.models.v2 import build_v2

    path = args.path.rstrip("/")
    name = os.path.basename(path)
    files: list[tuple[tuple[str, ...], str]] = []
    if os.path.isfile(path):
        files.append(((name,), path))
    else:
        for dirpath, _, names in sorted(os.walk(path)):
            for fn in sorted(names):
                fp = os.path.join(dirpath, fn)
                rel = os.path.relpath(fp, path)
                files.append((tuple(rel.split(os.sep)), fp))
    plen = args.piece_length or (1 << 20)
    kwargs = dict(
        name=name, piece_length=plen, hasher=args.hasher,
        announce=args.tracker, private=args.private, comment=args.comment,
        announce_list=[[t] for t in args.also_tracker] or None,
        web_seeds=args.web_seed or None,
    )
    try:
        if args.hybrid:
            from torrent_tpu.models.v2 import build_hybrid

            data, meta = build_hybrid(files, **kwargs)
            kind = "hybrid v1+v2"
        else:
            meta = build_v2(files, **kwargs)
            data = encode_metainfo_v2(
                meta.info, meta.piece_layers, announce=args.tracker,
                comment=args.comment,
                announce_list=[[t] for t in args.also_tracker] or None,
                web_seeds=args.web_seed or None,
            )
            kind = "v2"
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    similar = _parse_similar_args(args)
    if similar is None:
        return 2
    if similar or args.collection or args.update_url:
        # BEP 38/39 hints for v2/hybrid go in the ROOT dict (the BEPs'
        # mutable placement): the v2 info-dict builders don't carry
        # them, and top-level keys leave the infohash untouched
        from torrent_tpu.codec.bencode import bdecode, bencode

        top = bdecode(data)
        if similar:
            top[b"similar"] = similar
        if args.collection:
            top[b"collections"] = [c.encode("utf-8") for c in args.collection]
        if args.update_url:
            top[b"update-url"] = args.update_url.encode("utf-8")
        # canonical bencode wants sorted dict keys; the appended keys land
        # at the end of the decoded order, so shallow-sort the TOP level
        # only (the info value's bytes — and thus the infohash — are
        # untouched; sort_keys=False keeps nested dicts verbatim)
        top = {k: top[k] for k in sorted(top)}
        data = bencode(top, sort_keys=False)
    out = args.output or (name + ".torrent")
    with open(out, "wb") as f:
        f.write(data)
    print(
        f"wrote {out} ({len(data):,} bytes, {kind}, "
        f"infohash {meta.info_hash_v2.hex()[:16]}...)"
    )
    return 0


def _parse_similar_args(args) -> list[bytes] | None:
    """``--similar`` hex strings → infohash bytes; None after printing a
    CLI-style error on malformed input (a traceback is not an error
    message)."""
    out = []
    for h in getattr(args, "similar", []):
        try:
            raw = bytes.fromhex(h)
        except ValueError:
            raw = b""
        if len(raw) not in (20, 32):
            print(
                f"error: --similar {h!r} is not a 40- or 64-digit hex infohash",
                file=sys.stderr,
            )
            return None
        out.append(raw)
    return out


def _verify_v2(v2, args) -> int:
    import os

    from torrent_tpu.models.v2 import verify_v2

    root = os.path.join(args.dir, v2.info.name)
    # single-file convention matches v1 Storage: the payload lives at
    # <dir>/<name>, not <dir>/<name>/<name>
    single = len(v2.info.files) == 1 and v2.info.files[0].path == (v2.info.name,)

    def read_file(path):
        fp = root if single else os.path.join(root, *path)
        # parse_metainfo_v2 already rejects traversal components; this is
        # defense in depth for callers constructing MetainfoV2 directly
        if os.path.commonpath([os.path.abspath(fp), os.path.abspath(args.dir)]) != os.path.abspath(args.dir):
            return None
        if not os.path.isfile(fp):
            return None
        return fp  # path source: verify_v2 streams it

    res = verify_v2(read_file, v2, hasher=args.hasher)
    total = sum(len(ok) for ok in res.values())
    valid = sum(int(ok.sum()) for ok in res.values())
    for path, ok in res.items():
        if len(ok) and not ok.all():
            bad = [i for i in range(len(ok)) if not ok[i]]
            print(f"  {'/'.join(path)}: bad pieces {bad[:10]}")
    print(f"{valid}/{total} pieces valid (v2)")
    return 0 if valid == total else 2


def _cmd_verify(args) -> int:
    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.codec.metainfo_v2 import parse_metainfo_v2
    from torrent_tpu.parallel.verify import verify_pieces
    from torrent_tpu.storage.storage import FsStorage, Storage

    with open(args.torrent, "rb") as f:
        data = f.read()
    # v2-aware parse first: hybrids verify via the per-file merkle path
    # (pad files never exist on disk, so the v1 view would fail the
    # pieces that cover them); pure-v1 torrents fall through unchanged.
    v2 = parse_metainfo_v2(data)
    if v2 is not None:
        return _verify_v2(v2, args)
    m = parse_metainfo(data)
    if m is None:
        print("error: not a valid .torrent file", file=sys.stderr)
        return 1

    def progress(done, total):
        print(f"\rverified {done}/{total} pieces", end="", file=sys.stderr, flush=True)

    kwargs = {"batch_size": args.batch} if args.hasher == "tpu" else {}
    ok = verify_pieces(
        Storage(FsStorage(args.dir), m.info),
        m.info,
        hasher=args.hasher,
        progress_cb=progress,
        **kwargs,
    )
    print("", file=sys.stderr)
    valid = int(ok.sum())
    print(f"{valid}/{m.info.num_pieces} pieces valid")
    if valid < m.info.num_pieces:
        bad = [i for i in range(m.info.num_pieces) if not ok[i]]
        print(f"first invalid pieces: {bad[:10]}")
        return 2
    return 0


async def _seed_box(args) -> int:
    """Seed every .torrent in a directory against one data root — the
    long-running "seeding box" mode (no reference counterpart; its CLI
    roadmap stopped at a single-torrent proof of concept)."""
    import glob

    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.codec.metainfo_v2 import parse_metainfo_v2
    from torrent_tpu.session.client import Client, ClientConfig

    torrent_files = sorted(glob.glob(os.path.join(args.torrents, "*.torrent")))
    if not torrent_files:
        print(f"error: no .torrent files in {args.torrents!r}", file=sys.stderr)
        return 1
    config = ClientConfig(
        port=args.port,
        hasher=args.hasher,
        max_upload_bps=args.max_up * 1024,
        enable_lsd=args.lsd,
        enable_utp=args.utp,
    )
    if args.encryption:
        config.torrent.encryption = args.encryption
    if args.super_seed:
        config.torrent.super_seed = True
    client = Client(config)
    await client.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover - non-unix
            pass
    metrics_server = box_stream = None
    try:
        added = 0
        for path in torrent_files:
            if stop.is_set():
                # ctrl-c during a long recheck pass must not be absorbed
                # until the whole library has been hashed
                print("\ninterrupted during startup", file=sys.stderr)
                return 130
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError as e:
                print(f"skipping {path}: {e}", file=sys.stderr)
                continue
            m = parse_metainfo(data) or parse_metainfo_v2(data)
            if m is None:
                print(f"skipping {path}: not a valid .torrent", file=sys.stderr)
                continue
            try:
                t = await client.add(m, args.data)
            except ValueError as e:  # duplicate infohash etc.
                print(f"skipping {path}: {e}", file=sys.stderr)
                continue
            have = t.bitfield.count()
            print(
                f"seeding {os.path.basename(path)}: {have}/{t.info.num_pieces} pieces",
                file=sys.stderr,
            )
            added += 1
        if not added:
            print("error: nothing to seed", file=sys.stderr)
            return 1
        if args.metrics_port is not None:
            from torrent_tpu.utils.metrics import MetricsServer

            metrics_server = await MetricsServer(client).start(args.metrics_port)
            print(
                f"metrics http://127.0.0.1:{metrics_server.port}/metrics",
                file=sys.stderr,
            )
        if getattr(args, "stream_port", None) is not None:
            from torrent_tpu.tools.stream import BoxStreamServer

            box_stream = await BoxStreamServer(client).start(args.stream_port)
            print(
                f"streaming http://127.0.0.1:{box_stream.port}/ "
                "(/{infohash}/{file})",
                file=sys.stderr,
            )
        print(
            f"seeding {added} torrent(s) on port {client.port} (ctrl-c to stop)",
            file=sys.stderr,
        )

        async def report():
            while not stop.is_set():
                s = client.status()
                print(
                    f"\rpeers {s['peers']} up {s['uploaded']:,} down {s['downloaded']:,}   ",
                    end="",
                    file=sys.stderr,
                    flush=True,
                )
                await asyncio.sleep(2)

        reporter = asyncio.ensure_future(report())
        await stop.wait()
        reporter.cancel()
        return 0
    finally:
        if metrics_server is not None:
            metrics_server.close()
        if box_stream is not None:
            box_stream.close()
        await client.close()


def _cmd_seed(args) -> int:
    return asyncio.run(_seed_box(args))


def _read_seed_file(path: str) -> bytes | None:
    """32-byte Ed25519 seed from a key file: 64 hex chars or raw bytes."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        print(f"error: cannot read key file {path!r}: {e}", file=sys.stderr)
        return None
    text = raw.strip()
    if len(text) == 64:
        try:
            seed = bytes.fromhex(text.decode("ascii"))
        except (ValueError, UnicodeDecodeError):
            seed = b""
        # fromhex ignores internal whitespace, so 64 chars can still
        # yield a short seed — diagnose HERE, naming the file
        if len(seed) == 32:
            return seed
    if len(raw) == 32:
        return raw
    print(f"error: {path!r} is not a 32-byte seed (raw or 64 hex chars)",
          file=sys.stderr)
    return None


def _cmd_sign(args) -> int:
    """BEP 35 torrent signing (Ed25519 — the BEP 46 key format).

    ``--keygen`` mints a key pair; ``--signer NAME --key FILE`` signs;
    ``--check NAME --pub HEX`` verifies against the trusted key (exit 0
    valid / 2 invalid). ``--check NAME`` alone can only test
    self-consistency against the attacker-controlled embedded
    certificate, so it ALWAYS exits 2 (SELF-CONSISTENT/UNTRUSTED or
    INVALID) — exit 0 is reachable only with ``--pub``.
    Signing is root-level only: the infohash never changes.
    """
    from torrent_tpu.codec import signing

    if args.keygen:
        if not args.key:
            print("error: --keygen needs --key FILE to write", file=sys.stderr)
            return 2
        if os.path.exists(args.key):
            print(f"error: {args.key!r} exists; refusing to overwrite a key",
                  file=sys.stderr)
            return 2
        from torrent_tpu.utils import ed25519

        seed = os.urandom(32)
        try:
            fd = os.open(args.key, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(seed.hex() + "\n")
        except OSError as e:
            print(f"error: cannot write key file {args.key!r}: {e}",
                  file=sys.stderr)
            return 1
        print(f"wrote {args.key} (keep it secret)")
        print(f"public key: {ed25519.publickey(seed).hex()}")
        return 0

    if not args.torrent:
        print("error: missing .torrent argument", file=sys.stderr)
        return 2
    try:
        with open(args.torrent, "rb") as f:
            data = f.read()
    except OSError as e:
        print(f"error: cannot read {args.torrent!r}: {e}", file=sys.stderr)
        return 1

    if args.check is not None:
        pub = None
        if args.pub:
            try:
                pub = bytes.fromhex(args.pub)
            except ValueError:
                print("error: --pub must be hex", file=sys.stderr)
                return 2
            if len(pub) != 32:
                # a wrong-length key is a usage error, not an invalid
                # signature — misreporting it as INVALID misdiagnoses
                # a perfectly good torrent as tampered
                print(
                    f"error: --pub must be 32 bytes (64 hex chars), got "
                    f"{len(pub)}",
                    file=sys.stderr,
                )
                return 2
        if pub is None:
            # no trusted key given: a certificate-less entry is
            # UNVERIFIABLE, not invalid — don't misdiagnose an
            # out-of-band-key torrent as tampered
            if args.check in signing.list_signers(
                data
            ) and not signing.has_embedded_certificate(data, args.check):
                print(
                    f"signature by {args.check!r}: UNVERIFIABLE "
                    f"(no embedded certificate — provide --pub KEY)"
                )
                return 2
        ok = signing.verify_torrent(data, args.check, pub)
        if pub is not None:
            print(f"signature by {args.check!r}: "
                  f"{'VALID' if ok else 'INVALID'} (trusted key)")
            return 0 if ok else 2
        # Embedded-certificate-only: self-consistency, NOT trust. A
        # tampered torrent whose cert+signature were replaced together
        # passes this check, so the bare --check form must never be a
        # scriptable exit-0 "valid" (advisor r4): report loudly and
        # exit non-zero either way.
        if ok:
            print(
                f"signature by {args.check!r}: SELF-CONSISTENT "
                f"(embedded certificate — UNTRUSTED: anyone can re-sign "
                f"with a fresh key; pass --pub KEY for a trusted verdict)"
            )
        else:
            print(f"signature by {args.check!r}: INVALID (embedded certificate)")
        return 2

    if not args.key or not args.signer:
        print("error: signing needs --key FILE and --signer NAME",
              file=sys.stderr)
        return 2
    seed = _read_seed_file(args.key)
    if seed is None:
        return 1
    try:
        signed = signing.sign_torrent(data, seed, args.signer)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    out = args.output or args.torrent
    tmp = out + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(signed)
        os.replace(tmp, out)
    except OSError as e:
        print(f"error: cannot write {out!r}: {e}", file=sys.stderr)
        return 1
    names = ", ".join(signing.list_signers(signed))
    print(f"wrote {out} ({len(signed):,} bytes; signed by: {names})")
    return 0


async def _fabric_verify(args) -> int:
    """One process of a pod-scale scheduler-fed library recheck
    (torrent_tpu/fabric). Mirrors tests/distributed_worker.py's process
    flags: ``--coordinator/--num-processes/--process-id`` join a real
    ``jax.distributed`` cluster (``--cpu-devices K`` pins K virtual CPU
    devices first, for CPU test rigs); ``--num-processes/--process-id``
    WITHOUT a coordinator runs over the shared-filesystem heartbeat
    transport (``--heartbeat-dir``) with no collective at all — the
    mode that survives a killed worker via lapse adoption."""
    import glob
    import json

    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.storage.storage import FsStorage, Storage

    if args.cpu_devices:
        # stage the XLA flag BEFORE jax import: on jax < 0.5 (no
        # jax_num_cpu_devices config) the virtual CPU device count is
        # parsed once at backend init (same shim as __graft_entry__)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.cpu_devices}"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:  # newer jax: the config knob exists and wins
            jax.config.update("jax_num_cpu_devices", args.cpu_devices)
        except AttributeError:
            pass
    nproc, pid = args.num_processes, args.process_id
    if (nproc is None) != (pid is None):
        print(
            "error: --num-processes and --process-id go together",
            file=sys.stderr,
        )
        return 2
    if args.coordinator:
        if nproc is None:
            print(
                "error: --coordinator needs --num-processes and --process-id",
                file=sys.stderr,
            )
            return 2
        from torrent_tpu.parallel.distributed import initialize

        initialize(args.coordinator, nproc, pid)
    if nproc is not None and nproc > 1 and not (
        args.coordinator or args.heartbeat_dir
    ):
        print(
            "error: multi-process fabric needs a transport: --coordinator "
            "(jax.distributed allgather) or --heartbeat-dir (shared "
            "filesystem)",
            file=sys.stderr,
        )
        return 2
    if args.die_after_units is not None and not args.heartbeat_dir:
        print(
            "error: --die-after-units needs --heartbeat-dir (file transport)",
            file=sys.stderr,
        )
        return 2

    torrent_files = sorted(glob.glob(os.path.join(args.torrents, "*.torrent")))
    if not torrent_files:
        print(f"error: no .torrent files in {args.torrents!r}", file=sys.stderr)
        return 1
    items = []
    for tf in torrent_files:
        with open(tf, "rb") as f:
            meta = parse_metainfo(f.read())
        if meta is None:
            print(f"skipping {tf}: not a v1 .torrent (fabric is sha1-plane)",
                  file=sys.stderr)
            continue
        stem = os.path.splitext(os.path.basename(tf))[0]
        root = os.path.join(args.data, stem)
        if not os.path.isdir(root):
            root = args.data
        items.append((Storage(FsStorage(root), meta.info), meta.info))
    if not items:
        print("error: nothing to verify", file=sys.stderr)
        return 1

    from torrent_tpu.fabric import FabricConfig
    from torrent_tpu.obs.attrib import attribute
    from torrent_tpu.obs.ledger import pipeline_ledger
    from torrent_tpu.parallel.bulk import verify_library_fabric
    from torrent_tpu.sched import FaultPlan, HashPlaneScheduler, SchedulerConfig

    plane_factory = None
    forge_receipts = False
    if args.fault_plan:
        # deterministic chaos, same spec language as the bridge and
        # doctor (sched/faults.py) — e.g. latency_ms throttles h2d so
        # doctor --fleet can prove cross-process bottleneck attribution;
        # forge_receipts=1 turns THIS worker into the Byzantine liar
        # doctor --byzantine convicts
        try:
            fault_plan = FaultPlan.parse(args.fault_plan)
            forge_receipts = fault_plan.forge_receipts
            plane_factory = fault_plan.plane_factory(hasher=args.hasher)
        except ValueError as e:
            print(f"error: bad --fault-plan: {e}", file=sys.stderr)
            return 2
    sched = await HashPlaneScheduler(
        SchedulerConfig(
            batch_target=args.batch_target, plane_factory=plane_factory
        ),
        hasher=args.hasher,
    ).start()
    cfg = FabricConfig(
        heartbeat_interval=args.heartbeat_interval,
        lapse_after=args.lapse_after,
        fault_exit_after_units=args.die_after_units,
        byzantine_f=args.byzantine_f,
        audit_rate=args.audit_rate,
        audit_seed=args.audit_seed,
        forge_receipts=forge_receipts,
    )
    executors: list = []
    obs_server = None
    if args.obs_port is not None:
        # the worker's live observability surface: GET /v1/fleet (this
        # process's swarm rollup) + GET /metrics, so `top --fleet` and
        # doctor --fleet can watch the sweep from a peer's point of view
        from torrent_tpu.obs.fleet import FleetObsServer

        obs_server = await FleetObsServer(
            lambda: executors[0] if executors else None, sched
        ).start(args.obs_port)
        print(f"obs server on 127.0.0.1:{obs_server.port}", file=sys.stderr)
        if args.obs_port_file:
            tmp = args.obs_port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(obs_server.port))
            os.replace(tmp, args.obs_port_file)
    led_prev = pipeline_ledger().snapshot()
    try:
        res = await verify_library_fabric(
            items,
            sched,
            nproc=nproc,
            pid=pid,
            heartbeat_dir=args.heartbeat_dir,
            fabric_config=cfg,
            unit_bytes=(args.unit_mb << 20) if args.unit_mb else None,
            executor_out=executors,
        )
    finally:
        if obs_server is not None:
            obs_server.close()
        await sched.close()
    led_rep = attribute(pipeline_ledger().snapshot(), prev=led_prev)
    snap = executors[0].metrics_snapshot()
    payload = {
        "pid": snap["pid"],
        "nproc": snap["nproc"],
        "plan": snap["plan_fingerprint"],
        "bitfields": [
            "".join("1" if b else "0" for b in bf) for bf in res.bitfields
        ],
        "n_valid": int(sum(bf.sum() for bf in res.bitfields)),
        "n_pieces": res.n_pieces,
        "shard_units": snap["shard_units"],
        "shard_bytes": snap["shard_bytes"],
        "units_done": snap["units_done"],
        "units_adopted": snap["units_adopted"],
        "pieces_verified": snap["pieces_verified"],
        "sentinel_checks": snap["sentinel_checks"],
        "sentinel_mismatches": snap["sentinel_mismatches"],
        "byzantine_f": snap["byzantine_f"],
        "quorum_need": snap["quorum_need"],
        "audit_checks": snap["audit_checks"],
        "audit_mismatches": snap["audit_mismatches"],
        "convictions": snap["convictions"],
        "distrusted": snap["distrusted"],
        "stragglers": snap["stragglers"],
        "seconds": res.seconds,
        # this process's pipeline-ledger breakdown (bench fabric embeds
        # these per worker) and its final view of the fleet — which peer
        # limited the sweep, and which stage inside it
        "ledger": {
            "wall_s": led_rep["wall_s"],
            "stages": led_rep["stages"],
            "bottleneck": led_rep["bottleneck"],
            "overlap": led_rep.get("overlap"),
        },
        "fleet": executors[0].fleet_snapshot(),
    }
    line = json.dumps(payload)
    if args.result_file:
        # atomic, same contract as tests/distributed_worker.py's _emit:
        # concurrent C++/runtime stdout noise can garble the print
        tmp = args.result_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(line)
        os.replace(tmp, args.result_file)
    print(line)
    return 0 if payload["n_valid"] == payload["n_pieces"] else 2


def _cmd_fabric_verify(args) -> int:
    return asyncio.run(_fabric_verify(args))


def _cmd_lint(args) -> int:
    """Static concurrency/invariant analysis gate (torrent_tpu/analysis)."""
    from torrent_tpu.analysis.lint import main as lint_main

    argv = []
    if args.root:
        argv += ["--root", args.root]
    if args.baseline:
        argv += ["--baseline", args.baseline]
    if args.passes:
        argv += ["--passes", args.passes]
    if args.no_baseline:
        argv.append("--no-baseline")
    if args.update_baseline:
        argv.append("--update-baseline")
    if args.json:
        argv.append("--json")
    if args.graph:
        argv.append("--graph")
    if args.sarif:
        argv += ["--sarif", args.sarif]
    return lint_main(argv)


def _render_span_tree(tree: dict) -> str:
    """Human-readable span tree (torrent-tpu trace dump --id)."""
    lines = [
        f"trace {tree.get('trace_id')} — {tree.get('span_count', 0)} span(s)"
        + (
            f", {tree['dropped_spans']} dropped"
            if tree.get("dropped_spans")
            else ""
        )
    ]

    def walk(node: dict, depth: int) -> None:
        mark = "" if node.get("status") == "ok" else f" [{node.get('status')}]"
        attrs = node.get("attrs") or {}
        detail = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        lines.append(
            f"{'  ' * depth}{node.get('name')}  "
            f"+{node.get('start_ms', 0)}ms {node.get('duration_ms', 0)}ms"
            f"{mark}" + (f"  {detail}" if detail else "")
        )
        for child in node.get("children", ()):
            walk(child, depth + 1)

    for root in tree.get("spans", ()):
        walk(root, 1)
    return "\n".join(lines)


def _cmd_trace(args) -> int:
    """Fetch span trees / flight-recorder dumps (torrent_tpu/obs).

    ``torrent-tpu trace dump`` reads ``GET /v1/trace`` from a running
    bridge (``--id`` narrows to one trace's span tree); ``--dir`` reads
    the newest black-box file a flight recorder wrote to disk
    (``TORRENT_TPU_FLIGHT_DIR``) instead — the post-mortem path when
    the process is already gone.
    """
    import json as _json

    if args.dir:
        import glob

        # newest by mtime, not filename: dump seqs restart per process,
        # so a restarted service's fresh dumps must not be shadowed by a
        # previous run's higher-numbered leftovers
        files = sorted(
            glob.glob(os.path.join(args.dir, "blackbox_*.json")),
            key=os.path.getmtime,
        )
        if not files:
            print(f"error: no blackbox_*.json files in {args.dir!r}", file=sys.stderr)
            return 1
        with open(files[-1]) as f:
            dump = _json.load(f)
        if args.json:
            print(_json.dumps(dump, sort_keys=True))
        else:
            print(
                f"{files[-1]}: dump #{dump.get('seq')} ({dump.get('reason')}), "
                f"{len(dump.get('recent_spans', []))} recent spans, "
                f"{len(dump.get('traces', {}))} trace(s)"
            )
            print(_json.dumps(dump.get("detail", {}), sort_keys=True))
        return 0

    import http.client
    import urllib.error
    import urllib.parse
    import urllib.request

    url = args.url.rstrip("/") + "/v1/trace"
    if args.id:
        url += "?id=" + urllib.parse.quote(args.id, safe="")
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            payload = _json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        # the bridge answered — a 404 means the trace id is unknown,
        # not that the bridge is unreachable
        print(f"error: {url} returned {e.code} {e.reason}", file=sys.stderr)
        return 1
    except (OSError, ValueError, http.client.HTTPException) as e:
        print(f"error: cannot reach {url}: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(_json.dumps(payload, sort_keys=True))
        return 0
    if args.id:
        print(_render_span_tree(payload))
        return 0
    counts = payload.get("dump_counts", {})
    dumps = payload.get("dumps", [])
    print(
        f"flight recorder: {len(dumps)} dump(s) held"
        + (
            " — " + ", ".join(f"{k}×{v}" for k, v in sorted(counts.items()))
            if counts
            else ""
        )
    )
    for d in dumps:
        print(
            f"  #{d.get('seq')} {d.get('reason')}: "
            f"{_json.dumps(d.get('detail', {}), sort_keys=True)}"
        )
    traces = payload.get("traces", [])
    print(f"traces held: {len(traces)}")
    for tid in traces[-10:]:
        print(f"  {tid}")
    return 0


def _cmd_doctor(args) -> int:
    # run_cli, not main: the triage tool must not run its checks inside
    # an interpreter wired to the device plugin it is triaging — it
    # re-execs with the axon pool var stripped (the bounded device-probe
    # subprocess gets it back). See tools/doctor.py module docstring.
    from torrent_tpu.tools.doctor import run_cli as doctor_cli

    argv = ["--device-wait", str(args.device_wait)]
    if args.skip_swarm:
        argv.append("--skip-swarm")
    if getattr(args, "faults", False):
        argv.append("--faults")
    if getattr(args, "v2", False):
        argv.append("--v2")
    if getattr(args, "fabric", False):
        argv.append("--fabric")
    if getattr(args, "byzantine", False):
        argv.append("--byzantine")
    if getattr(args, "fleet", False):
        argv.append("--fleet")
    if getattr(args, "lint", False):
        argv.append("--lint")
    if getattr(args, "trace", False):
        argv.append("--trace")
    if getattr(args, "bottleneck", False):
        argv.append("--bottleneck")
    if getattr(args, "control", False):
        argv.append("--control")
    if getattr(args, "announce", False):
        argv.append("--announce")
    if getattr(args, "slo", False):
        argv.append("--slo")
    if getattr(args, "swarm", False):
        argv.append("--swarm")
    if getattr(args, "scenario", None):
        argv += ["--scenario", args.scenario]
    if getattr(args, "seed", False):
        argv.append("--seed")
    if getattr(args, "json", False):
        argv.append("--json")
    return doctor_cli(argv)


def _cmd_top(args) -> int:
    from torrent_tpu.tools.top import main as top_main

    argv = ["--url", args.url, "--interval", str(args.interval)]
    if args.once:
        argv.append("--once")
    if getattr(args, "fleet", False):
        argv.append("--fleet")
    if getattr(args, "history", False):
        argv.append("--history")
    if getattr(args, "swarm", False):
        argv.append("--swarm")
    return top_main(argv)


def _cmd_replay(args) -> int:
    """Offline post-mortem replay of a dumped timeline (obs/timeline):
    the live attributor re-run over historical sample deltas, so "what
    was limiting at T-5m" is answerable after the process is gone."""
    import json as _json

    from torrent_tpu.obs.attrib import format_rate
    from torrent_tpu.obs.slo import parse_objectives
    from torrent_tpu.obs.timeline import replay_report

    try:
        with open(args.file) as f:
            payload = _json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read timeline {args.file}: {e}", file=sys.stderr)
        return 2
    objectives = None
    if args.slo:
        try:
            objectives = parse_objectives(args.slo)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    rep = replay_report(payload, objectives=objectives)
    if args.json:
        print(_json.dumps(rep, sort_keys=True))
        return 0
    print(
        f"timeline replay: {rep['samples']} samples over {rep['span_s']:.1f}s"
        + (f" ({rep['drops']} dropped off the ring)" if rep["drops"] else "")
    )
    intervals = rep["intervals"][-max(1, args.intervals):]
    if not intervals:
        print("no sample intervals recorded")
        return 0
    print(f"{'age':>10s} {'limiting':10s} {'util':>6s} {'rate':>12s}  errors")
    for itv in intervals:
        sched = itv.get("sched") or {}
        errs = int(sched.get("shed", 0) or 0) + int(
            sched.get("failed_pieces", 0) or 0
        )
        print(
            f"T-{itv['age_s']:7.1f}s {itv.get('limiting') or '—':10s} "
            f"{(itv.get('utilization') or 0) * 100:5.0f}% "
            f"{format_rate(itv.get('pipeline_bps')):>12s}  "
            f"{errs if errs else '—'}"
        )
    overall = (rep.get("overall") or {}).get("bottleneck")
    if overall:
        print(
            f"overall: {overall['stage']} limited the span — "
            f"{overall.get('utilization', 0) * 100:.0f}% utilized, "
            f"{format_rate(overall.get('achieved_bps'))} achieved"
        )
    else:
        print("overall: pipeline idle across the span")
    from torrent_tpu.tools.top import format_slo_line

    slo = rep.get("slo")
    for name, obj in sorted(((slo or {}).get("objectives") or {}).items()):
        print(format_slo_line(name, obj))
    return 0


def _cmd_serve(args) -> int:
    from torrent_tpu.tools.serve import main as serve_main

    argv = [
        "--http-port", str(args.http_port),
        "--udp-port", str(args.udp_port),
        "--host", args.host,
        "--interval", str(args.interval),
        "--shards", str(args.shards),
        "--dht-port", str(args.dht_port),
        "--crawl-interval", str(args.crawl_interval),
        "--timeline-interval", str(args.timeline_interval),
    ]
    if args.slo is not None:
        argv.append("--slo")
        if args.slo is not True:
            argv.append(args.slo)
    return serve_main(argv)


def _cmd_bench(args) -> int:
    from torrent_tpu.tools.bench_cli import main as bench_main

    argv: list[str] = []
    if args.rung:
        argv.append(args.rung)
    if args.smoke:
        argv.append("--smoke")
    argv += ["--mb", str(args.mb), "--piece-kb", str(args.piece_kb),
             "--batch-target", str(args.batch_target),
             "--hasher", args.hasher,
             "--clients", str(args.clients), "--swarms", str(args.swarms),
             "--per-client", str(args.per_client),
             "--shards", str(args.shards), "--numwant", str(args.numwant),
             "--leechers", str(args.leechers),
             "--tolerance", str(args.tolerance)]
    if args.timeout is not None:
        argv += ["--timeout", str(args.timeout)]
    if args.out:
        argv += ["--out", args.out]
    if args.record:
        argv += ["--record", args.record]
    if args.trajectory:
        argv += ["--trajectory", args.trajectory]
    if args.compare:
        argv.append("--compare")
    if args.report_only:
        argv.append("--report-only")
    if args.bank:
        argv.append("--bank")
    return bench_main(argv)


def _cmd_edit(args) -> int:
    """Rewrite a .torrent's top-level fields without touching the info
    dict: the infohash (and thus the swarm) is preserved byte-for-byte,
    which re-authoring cannot guarantee."""
    from torrent_tpu.codec.bencode import bdecode_with_info_span, bencode

    try:
        with open(args.torrent, "rb") as f:
            data = f.read()
    except OSError as e:
        print(f"error: cannot read {args.torrent!r}: {e}", file=sys.stderr)
        return 1
    try:
        top, span = bdecode_with_info_span(data)
    except Exception as e:
        print(f"error: not a valid .torrent: {e}", file=sys.stderr)
        return 1
    if not isinstance(top, dict) or span is None:
        print("error: no info dict found", file=sys.stderr)
        return 1
    raw_info = data[span[0] : span[1]]

    if args.tracker:
        top[b"announce"] = args.tracker[0].encode()
        if len(args.tracker) > 1 or b"announce-list" in top:
            top[b"announce-list"] = [[t.encode()] for t in args.tracker]
    if args.clear_trackers:
        top.pop(b"announce-list", None)
        top[b"announce"] = b""  # schema requires the key; empty = trackerless
    if args.web_seed:
        top[b"url-list"] = [u.encode() for u in args.web_seed]
    if args.clear_web_seeds:
        top.pop(b"url-list", None)
        top.pop(b"httpseeds", None)
    if args.comment is not None:
        if args.comment:
            top[b"comment"] = args.comment.encode()
        else:
            top.pop(b"comment", None)

    # re-encode everything EXCEPT the info dict, which is spliced back
    # raw so the infohash cannot shift (bencode canonicalization of a
    # foreign encoder's info dict could change it)
    head = (
        b"d"
        + b"".join(
            bencode(k) + (raw_info if k == b"info" else bencode(top[k]))
            for k in sorted(set(top) | {b"info"})
        )
        + b"e"
    )
    out_path = args.output or args.torrent
    # atomic: a full disk or interrupt mid-write must never leave the
    # (possibly only) copy truncated
    tmp = out_path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(head)
    os.replace(tmp, out_path)
    import hashlib as _h

    print(f"wrote {out_path} (infohash {_h.sha1(raw_info).hexdigest()} unchanged)")
    return 0


async def _download(args) -> int:
    from torrent_tpu.session.client import Client, ClientConfig

    bootstrap = []
    for spec in args.dht_bootstrap:
        addr = _parse_hostport(spec)
        if addr is None:
            print(f"error: bad --dht-bootstrap {spec!r}", file=sys.stderr)
            return 1
        bootstrap.append(addr)
    config = ClientConfig(
        port=args.port,
        hasher=args.hasher,
        resume=not args.no_resume,
        enable_dht=args.dht or bool(bootstrap) or bool(getattr(args, "dht_state", "")),
        dht_bootstrap=tuple(bootstrap),
        dht_state_path=getattr(args, "dht_state", "") or "",
        max_upload_bps=args.max_up * 1024,
        max_download_bps=args.max_down * 1024,
        enable_lsd=args.lsd,
        enable_utp=args.utp,
        proxy=getattr(args, "proxy", "") or "",
    )
    if args.sequential:
        config.torrent.sequential = True
    if getattr(args, "super_seed", False):
        config.torrent.super_seed = True
    if getattr(args, "encryption", None):
        config.torrent.encryption = args.encryption
    try:
        client = Client(config)
    except ValueError as e:
        # e.g. --proxy with --dht/--lsd: a clean CLI error, not a traceback
        print(f"error: {e}", file=sys.stderr)
        return 1
    await client.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover - non-unix
            pass
    stream_server = metrics_server = None
    try:
        if args.source.startswith("magnet:"):
            if getattr(args, "require_signed", None):
                # BEP 35 signatures live at the torrent's ROOT — swarm
                # metadata (BEP 9) carries only the info dict, so a
                # magnet can never satisfy the gate; refuse honestly
                print(
                    "error: --require-signed needs a .torrent file "
                    "(magnet metadata cannot carry BEP 35 signatures)",
                    file=sys.stderr,
                )
                return 2
            print("fetching metadata from swarm...", file=sys.stderr)
            torrent = await client.add_magnet(args.source, args.dir)
        else:
            with open(args.source, "rb") as f:
                data = f.read()
            req = getattr(args, "require_signed", None)
            if req:
                from torrent_tpu.codec import signing

                parsed_req = _parse_require_signed(req)
                if parsed_req is None:
                    return 2
                try:
                    signing.ensure_signed(data, *parsed_req)
                except ValueError as e:
                    print(f"error: refusing {args.source!r}: {e}",
                          file=sys.stderr)
                    return 2
            try:
                torrent = await client.add_torrent_bytes(data, args.dir)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
        if args.files:
            try:
                wanted = sorted({int(x) for x in args.files.split(",")})
                await torrent.select_files(wanted)
            except (ValueError, IndexError) as e:
                print(f"error: bad --files selection: {e}", file=sys.stderr)
                return 1
            print(f"downloading files {wanted} only", file=sys.stderr)
        print(f"listening on port {client.port}", file=sys.stderr)

        async def report():
            while not stop.is_set():
                s = torrent.status()
                print(
                    f"\r[{s['state']}] pieces {s['pieces']} peers {s['peers']} "
                    f"down {s['downloaded']:,} up {s['uploaded']:,}   ",
                    end="",
                    file=sys.stderr,
                    flush=True,
                )
                await asyncio.sleep(1)

        if getattr(args, "metrics_port", None) is not None:
            from torrent_tpu.utils.metrics import MetricsServer

            metrics_server = await MetricsServer(client).start(args.metrics_port)
            print(
                f"metrics http://127.0.0.1:{metrics_server.port}/metrics",
                file=sys.stderr,
            )
        if getattr(args, "stream_port", None) is not None:
            from torrent_tpu.tools.stream import StreamServer

            stream_server = await StreamServer(torrent).start(args.stream_port)
            from torrent_tpu.tools.stream import content_files

            for i, name, _, _ in content_files(torrent):
                print(
                    f"streaming http://127.0.0.1:{stream_server.port}/{i}  ({name})",
                    file=sys.stderr,
                )
        reporter = asyncio.ensure_future(report())
        done_wait = asyncio.ensure_future(torrent.on_complete.wait())
        stop_wait = asyncio.ensure_future(stop.wait())
        await asyncio.wait({done_wait, stop_wait}, return_when=asyncio.FIRST_COMPLETED)
        if torrent.on_complete.is_set():
            print("\ndownload complete", file=sys.stderr)
            if (args.seed or stream_server is not None) and not stop.is_set():
                print(
                    "seeding/streaming (ctrl-c to stop)"
                    if stream_server is not None
                    else "seeding (ctrl-c to stop)",
                    file=sys.stderr,
                )
                await stop.wait()
        reporter.cancel()
        done_wait.cancel()
        stop_wait.cancel()
        return 0 if torrent.on_complete.is_set() else 130
    finally:
        # sidecar servers close on every exit path, not just success
        if stream_server is not None:
            stream_server.close()
        if metrics_server is not None:
            metrics_server.close()
        await client.close()


def _cmd_download(args) -> int:
    return asyncio.run(_download(args))


def _cmd_scrape(args) -> int:
    from torrent_tpu.net.tracker import TrackerError, scrape

    hashes = []
    if args.torrent:
        from torrent_tpu.codec.metainfo import parse_metainfo

        with open(args.torrent, "rb") as f:
            m = parse_metainfo(f.read())
        if m is None:
            print("error: not a valid .torrent file", file=sys.stderr)
            return 1
        hashes.append(m.info_hash)
        url = args.url or m.announce
    else:
        url = args.url
    for h in args.info_hash:
        try:
            raw = bytes.fromhex(h)
        except ValueError:
            print(f"error: bad info hash {h!r}", file=sys.stderr)
            return 1
        if len(raw) != 20:
            print(f"error: info hash must be 40 hex chars: {h!r}", file=sys.stderr)
            return 1
        hashes.append(raw)
    if not url or not hashes:
        print("error: need a tracker URL and at least one info hash", file=sys.stderr)
        return 1

    proxy = None
    if getattr(args, "proxy", ""):
        from torrent_tpu.net.socks import ProxySpec

        try:
            proxy = ProxySpec.parse(args.proxy)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1

    async def go():
        try:
            entries = await scrape(url, hashes, proxy=proxy)
        except TrackerError as e:
            print(f"scrape failed: {e}", file=sys.stderr)
            return 1
        # key by the entry's own hash — HTTP trackers return files in
        # their own order and may omit hashes they don't know
        by_hash = {e.info_hash: e for e in entries}
        for h in hashes:
            e = by_hash.get(h)
            if e is None:
                print(f"{h.hex()}  (unknown to tracker)")
            else:
                print(
                    f"{h.hex()}  seeders={e.complete} leechers={e.incomplete} "
                    f"downloaded={e.downloaded}"
                )
        return 0

    return asyncio.run(go())


def _cmd_tracker(args) -> int:
    base = ["--http-port", str(args.http_port),
            "--udp-port", str(args.udp_port),
            "--interval", str(args.interval)]
    if getattr(args, "shards", 0) > 0:
        if args.state_file:
            # refuse rather than silently drop persistence: the sharded
            # plane has no snapshot file (persistent-tracker semantics
            # come from the DHT indexer seam), and an operator relying
            # on --state-file must learn that BEFORE losing state
            print(
                "error: --state-file is not supported with --shards "
                "(the sharded plane persists swarms via the DHT indexer, "
                "not a snapshot file)",
                file=sys.stderr,
            )
            return 2
        from torrent_tpu.server.shard import main as shard_main

        return shard_main(base + ["--shards", str(args.shards)])
    from torrent_tpu.server.in_memory import main as tracker_main

    return tracker_main(
        base + (["--state-file", args.state_file] if args.state_file else [])
    )


def _cmd_bridge(args) -> int:
    from torrent_tpu.bridge.service import main as bridge_main

    return bridge_main(
        [
            "--port", str(args.port),
            "--hasher", args.hasher,
            "--batch-target", str(args.batch_target),
            "--flush-deadline-ms", str(args.flush_deadline_ms),
            "--max-queue-mb", str(args.max_queue_mb),
            "--tenant-max-mb", str(args.tenant_max_mb),
        ]
        + (["--autopilot", "--autopilot-interval", str(args.autopilot_interval)]
           if args.autopilot else [])
        + ((["--slo"] + ([] if args.slo is True else [args.slo])
            + ["--timeline-interval", str(args.timeline_interval)])
           if args.slo is not None else [])
        + (["--fault-plan", args.fault_plan] if args.fault_plan else [])
        + (["--dev"] if args.dev else [])
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="torrent-tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("info", help="print .torrent metadata")
    sp.add_argument("torrent")
    sp.set_defaults(fn=_cmd_info)

    sp = sub.add_parser("magnet", help="emit a magnet URI for a .torrent")
    sp.add_argument("torrent")
    sp.add_argument(
        "--no-trackers", action="store_true", help="omit tr= parameters"
    )
    sp.add_argument(
        "--peer",
        action="append",
        default=[],
        metavar="HOST:PORT",
        help="x.pe bootstrap address (repeatable)",
    )
    sp.set_defaults(fn=_cmd_magnet)

    sp = sub.add_parser("make", help="author a .torrent (TPU-batched hashing)")
    sp.add_argument("path")
    sp.add_argument("tracker")
    sp.add_argument("-o", "--output")
    sp.add_argument("--comment")
    sp.add_argument("--piece-length", type=int)
    sp.add_argument("--hasher", choices=("cpu", "tpu"), default="cpu")
    sp.add_argument("--also-tracker", action="append", default=[],
                    help="extra tracker tier (BEP 12, repeatable)")
    sp.add_argument("--private", action="store_true", help="BEP 27 private flag")
    sp.add_argument(
        "--pad-files",
        action="store_true",
        help="BEP 47: piece-align every file with pad entries (multi-file)",
    )
    sp.add_argument("--web-seed", action="append", default=[],
                    help="BEP 19 url-list entry (repeatable)")
    sp.add_argument("--similar", action="append", default=[],
                    help="BEP 38: hex infohash of a torrent sharing files (repeatable)")
    sp.add_argument("--collection", action="append", default=[],
                    help="BEP 38: collection name grouping related torrents (repeatable)")
    sp.add_argument("--update-url",
                    help="BEP 39: URL where updated versions of this torrent appear")
    sp.add_argument("--v2", action="store_true",
                    help="author a BitTorrent v2 (BEP 52) torrent: SHA-256 merkle file tree")
    sp.add_argument("--hybrid", action="store_true",
                    help="author a hybrid v1+v2 torrent (BEP 52 upgrade path, BEP 47 pad files)")
    sp.set_defaults(fn=_cmd_make)

    sp = sub.add_parser(
        "sign", help="BEP 35: sign a .torrent / verify signatures / keygen"
    )
    sp.add_argument("torrent", nargs="?", help=".torrent file")
    sp.add_argument("--key", help="Ed25519 seed file (64 hex chars or raw 32B)")
    sp.add_argument("--signer", help="identity string for the signature entry")
    sp.add_argument("-o", "--output", help="write here instead of in place")
    sp.add_argument("--check", metavar="SIGNER",
                    help="verify SIGNER's signature instead of signing")
    sp.add_argument("--pub", help="trusted public key (hex) for --check")
    sp.add_argument("--keygen", action="store_true",
                    help="generate a new key pair into --key")
    sp.set_defaults(fn=_cmd_sign)

    sp = sub.add_parser("verify", help="recheck downloaded data against a .torrent")
    sp.add_argument("torrent")
    sp.add_argument("dir")
    sp.add_argument("--hasher", choices=("cpu", "tpu"), default="cpu")
    sp.add_argument("--batch", type=int, default=256)
    sp.set_defaults(fn=_cmd_verify)

    sp = sub.add_parser(
        "feed", help="BEP 36: subscribe to a torrent RSS/Atom feed"
    )
    sp.add_argument("url", help="feed URL (RSS 2.0 or Atom)")
    sp.add_argument("dir", help="download directory for added torrents")
    sp.add_argument("--interval", type=float, default=300,
                    help="poll interval in seconds (default 300)")
    sp.add_argument("--once", action="store_true",
                    help="poll once, print what was added, exit")
    sp.add_argument("--seen",
                    help="file remembering added entry URLs across runs "
                         "(one per line; created if missing)")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--proxy", help="SOCKS5 proxy URL")
    sp.add_argument(
        "--require-signed",
        metavar="SIGNER=PUBHEX",
        help="only add feed entries whose .torrent carries a valid "
        "BEP 35 signature by SIGNER under this trusted Ed25519 key "
        "(magnet entries are refused under the gate)",
    )
    sp.set_defaults(fn=_cmd_feed)

    sp = sub.add_parser(
        "update", help="BEP 39: poll a torrent's update-url for a successor"
    )
    sp.add_argument("torrent")
    sp.add_argument("-o", "--output",
                    help="where to write the successor .torrent "
                         "(default: alongside the original as NAME.updated.torrent)")
    sp.add_argument("--check", action="store_true",
                    help="only report whether an update exists (write nothing)")
    sp.add_argument("--proxy", help="SOCKS5 proxy URL for the fetch")
    sp.add_argument(
        "--require-signed",
        metavar="SIGNER=PUBHEX",
        help="refuse the successor unless it carries a valid BEP 35 "
        "signature by SIGNER under this trusted Ed25519 key "
        "(an update-url takeover cannot push an unsigned replacement)",
    )
    sp.set_defaults(fn=_cmd_update)

    sp = sub.add_parser("download", help="download a .torrent file or magnet URI")
    sp.add_argument("source", help=".torrent path or magnet:?xt=urn:btih:... URI")
    sp.add_argument("dir")
    sp.add_argument(
        "--require-signed",
        metavar="SIGNER=PUBHEX",
        help="refuse the .torrent unless it carries a valid BEP 35 "
        "signature by SIGNER under this trusted Ed25519 key",
    )
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--hasher", choices=("cpu", "tpu"), default="cpu")
    sp.add_argument("--seed", action="store_true", help="keep seeding after completion")
    sp.add_argument(
        "--super-seed",
        action="store_true",
        help="BEP 16 super-seeding while complete (reveal pieces one-by-one)",
    )
    sp.add_argument("--no-resume", action="store_true", help="skip fastresume checkpoints")
    sp.add_argument(
        "--encryption",
        choices=("disabled", "enabled", "required"),
        default="enabled",
        help="MSE/PE protocol encryption policy (default: enabled)",
    )
    sp.add_argument(
        "--proxy",
        default="",
        help="SOCKS5 proxy for TCP peers + HTTP trackers "
        "(socks5://[user:pass@]host:port; UDP paths are disabled)",
    )
    sp.add_argument(
        "--stream-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve files over HTTP (Range-capable) WHILE downloading; "
        "the reader position steers piece priority (0 = ephemeral port)",
    )
    sp.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="Prometheus /metrics endpoint for session counters (0 = ephemeral)",
    )
    sp.add_argument(
        "--files",
        metavar="I,J,...",
        help="download only these file indices (see `info` for the list)",
    )
    sp.add_argument(
        "--max-up", type=int, default=0, metavar="KIB_S",
        help="upload cap in KiB/s (0 = unlimited)",
    )
    sp.add_argument(
        "--max-down", type=int, default=0, metavar="KIB_S",
        help="download cap in KiB/s (0 = unlimited)",
    )
    sp.add_argument("--dht", action="store_true", help="enable BEP 5 mainline DHT discovery")
    sp.add_argument(
        "--lsd", action="store_true", help="enable BEP 14 local service discovery"
    )
    sp.add_argument(
        "--sequential",
        action="store_true",
        help="download pieces in order (streaming) instead of rarest-first",
    )
    sp.add_argument(
        "--utp",
        action="store_true",
        help="enable BEP 29 uTP transport (prefer uTP dials, TCP fallback)",
    )
    sp.add_argument(
        "--dht-bootstrap",
        action="append",
        default=[],
        metavar="HOST:PORT",
        help="DHT bootstrap node (repeatable; implies --dht)",
    )
    sp.add_argument(
        "--dht-state",
        default="",
        metavar="FILE",
        help="persist the DHT routing table here for seedless fast "
        "restarts (implies --dht)",
    )
    sp.set_defaults(fn=_cmd_download)

    sp = sub.add_parser("scrape", help="scrape seeder/leecher stats from a tracker")
    sp.add_argument(
        "--proxy",
        default="",
        help="SOCKS5 proxy for the scrape (socks5://[user:pass@]host:port)",
    )
    sp.add_argument("--url", help="tracker announce URL (derived from --torrent if omitted)")
    sp.add_argument("--torrent", help=".torrent whose tracker + hash to scrape")
    sp.add_argument("info_hash", nargs="*", help="40-hex info hashes")
    sp.set_defaults(fn=_cmd_scrape)

    sp = sub.add_parser(
        "edit", help="rewrite trackers/webseeds/comment without changing the infohash"
    )
    sp.add_argument("torrent")
    sp.add_argument("-o", "--output", help="write here instead of in place")
    trackers = sp.add_mutually_exclusive_group()
    trackers.add_argument(
        "--tracker", action="append", default=[], help="replace announce (+tiers)"
    )
    trackers.add_argument("--clear-trackers", action="store_true")
    sp.add_argument(
        "--web-seed", action="append", default=[], help="replace BEP 19 url-list"
    )
    sp.add_argument("--clear-web-seeds", action="store_true")
    sp.add_argument("--comment", default=None, help="set ('' removes)")
    sp.set_defaults(fn=_cmd_edit)

    sp = sub.add_parser(
        "seed", help="seed every .torrent in a directory (seeding-box mode)"
    )
    sp.add_argument("torrents", help="directory of .torrent files")
    sp.add_argument("data", help="data root the torrents' content lives under")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--hasher", choices=("cpu", "tpu"), default="cpu")
    sp.add_argument("--max-up", type=int, default=0, metavar="KiB/s")
    sp.add_argument("--lsd", action="store_true", help="BEP 14 local discovery")
    sp.add_argument("--utp", action="store_true", help="BEP 29 uTP transport")
    sp.add_argument(
        "--encryption", choices=("disabled", "enabled", "required"), default=""
    )
    sp.add_argument("--super-seed", action="store_true", help="BEP 16 on every torrent")
    sp.add_argument("--metrics-port", type=int, default=None, metavar="PORT")
    sp.add_argument(
        "--stream-port",
        type=int,
        default=None,
        metavar="PORT",
        help="HTTP media server over every torrent: / lists torrents, "
        "/<infohash>/<file> streams (0 = ephemeral)",
    )
    sp.set_defaults(fn=_cmd_seed)

    sp = sub.add_parser(
        "fabric-verify",
        help="one process of a pod-scale scheduler-fed library recheck",
    )
    sp.add_argument("torrents", help="directory of .torrent files")
    sp.add_argument("data", help="data root (per-torrent subdir or flat)")
    sp.add_argument("--hasher", choices=("cpu", "tpu"), default="cpu")
    sp.add_argument("--batch-target", type=int, default=256,
                    help="scheduler pieces-per-launch target")
    sp.add_argument("--coordinator", metavar="HOST:PORT",
                    help="jax.distributed coordinator (mirrors "
                    "tests/distributed_worker.py; enables the DCN "
                    "allgather heartbeat)")
    sp.add_argument("--num-processes", type=int, default=None)
    sp.add_argument("--process-id", type=int, default=None)
    sp.add_argument("--cpu-devices", type=int, default=0, metavar="K",
                    help="pin K virtual CPU devices before backend init "
                    "(jax_num_cpu_devices; CPU test rigs)")
    sp.add_argument("--heartbeat-dir", default=None, metavar="DIR",
                    help="shared-filesystem heartbeat transport (lapse "
                    "adoption; no jax.distributed needed)")
    sp.add_argument("--heartbeat-interval", type=float, default=0.5)
    sp.add_argument("--lapse-after", type=float, default=5.0,
                    help="seconds of heartbeat silence before a peer's "
                    "units are adopted (file transport)")
    sp.add_argument("--unit-mb", type=int, default=0,
                    help="work-unit size bound in MiB (0 = default 64)")
    sp.add_argument("--result-file", default=None,
                    help="also write the JSON result line here (atomic)")
    sp.add_argument("--obs-port", type=int, default=None, metavar="PORT",
                    help="serve GET /v1/fleet + /metrics on this loopback "
                    "port while the sweep runs (0 = ephemeral) — the "
                    "surface `torrent-tpu top --fleet` and doctor "
                    "--fleet watch")
    sp.add_argument("--obs-port-file", default=None, metavar="FILE",
                    help="write the bound obs-server port here (atomic; "
                    "for --obs-port 0 callers)")
    sp.add_argument("--fault-plan", default=None, metavar="SPEC",
                    help="inject deterministic hash-plane faults "
                    "(sched/faults.py spec, e.g. 'latency_ms=200' to "
                    "throttle h2d, 'forge_receipts=1' to lie at the "
                    "verdict layer); doctor --fleet / --byzantine use "
                    "this to prove attribution and conviction")
    sp.add_argument("--byzantine-f", type=int, default=0, metavar="F",
                    help="lying processes tolerated: f+1 replicas verify "
                    "each unit, verdicts carry Merkle receipt roots, "
                    "claims are audit-sampled, coverage needs f+1 "
                    "matching receipts (0 = trusted fast path)")
    sp.add_argument("--audit-rate", type=float, default=0.05,
                    help="per-(peer,unit,piece,round) audit probability "
                    "at --byzantine-f > 0 (deterministic given the plan "
                    "fingerprint + --audit-seed)")
    sp.add_argument("--audit-seed", type=int, default=0,
                    help="audit-sampling seed (same seed = bit-identical "
                    "audit schedule)")
    # deterministic worker-death injection for doctor --fabric / tests
    sp.add_argument("--die-after-units", type=int, default=None,
                    help=argparse.SUPPRESS)
    sp.set_defaults(fn=_cmd_fabric_verify)

    sp = sub.add_parser(
        "lint",
        help="concurrency/invariant static analysis (lock order, "
        "blocking-in-async, device-under-lock, determinism, "
        "guarded-state, lifecycle)",
    )
    sp.add_argument("--root", default=None,
                    help="package dir to lint (default: installed torrent_tpu)")
    sp.add_argument("--baseline", default=None,
                    help="baseline JSON (default: analysis_baseline.json "
                    "next to the package)")
    sp.add_argument("--passes", default=None, metavar="A,B",
                    help="comma-separated pass subset")
    sp.add_argument("--no-baseline", action="store_true",
                    help="raw findings; exit 1 if any")
    sp.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline, keeping justifications")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable findings report")
    sp.add_argument("--graph", action="store_true",
                    help="dump the static lock-acquisition graph and the "
                    "inferred attr->guard map")
    sp.add_argument("--sarif", default=None, metavar="PATH",
                    help="write findings as SARIF 2.1.0 for CI annotation")
    sp.set_defaults(fn=_cmd_lint)

    sp = sub.add_parser(
        "trace",
        help="ticket-lifecycle tracing: span trees and flight-recorder "
        "dumps from a running bridge (torrent_tpu/obs)",
    )
    sp.add_argument("action", choices=("dump",),
                    help="dump: fetch GET /v1/trace (all dumps + trace ids, "
                    "or one span tree with --id)")
    sp.add_argument("--url", default="http://127.0.0.1:8421",
                    help="bridge base URL (default %(default)s)")
    sp.add_argument("--id", default=None, metavar="TRACE",
                    help="trace id (the X-Trace-Id a request carried/got "
                    "back) to fetch as an ordered span tree")
    sp.add_argument("--dir", default=None, metavar="DIR",
                    help="read the newest blackbox_*.json from DIR "
                    "(TORRENT_TPU_FLIGHT_DIR) instead of a bridge — the "
                    "post-mortem path")
    sp.add_argument("--json", action="store_true",
                    help="raw JSON instead of the rendered tree/summary")
    sp.set_defaults(fn=_cmd_trace)

    sp = sub.add_parser(
        "doctor", help="environment triage: deps, device, kernels, swarm smoke"
    )
    sp.add_argument("--device-wait", type=float, default=20.0)
    sp.add_argument("--skip-swarm", action="store_true")
    sp.add_argument("--faults", action="store_true",
                    help="also run the fault-tolerance smoke: injected "
                    "fail-then-recover plan proving bisection isolation "
                    "and breaker trip/recovery")
    sp.add_argument("--v2", action="store_true",
                    help="also run the BEP 52 plane smoke: leaf + "
                    "merkle-pair digests vs hashlib through the pallas "
                    "sha256 lane (interpret-safe)")
    sp.add_argument("--bottleneck", action="store_true",
                    help="also run the pipeline-ledger smoke: a "
                    "scheduler-fed recheck attributed stage by stage; "
                    "with --faults the H2D stage is latency-throttled "
                    "and the attributor must name it")
    sp.add_argument("--control", action="store_true",
                    help="also run the scheduler-autopilot smoke: an "
                    "h2d-throttled scheduler must get its lane target "
                    "grown and its admission budget pulled toward the "
                    "limiting stage (controller-off moves nothing)")
    sp.add_argument("--fabric", action="store_true",
                    help="also run the verify-fabric self-test: two local "
                    "worker processes plan/execute/heartbeat, one dies "
                    "mid-run, the survivor adopts its shard")
    sp.add_argument("--byzantine", action="store_true",
                    help="also run the Byzantine-fabric self-test: two "
                    "workers at byzantine_f=1, one publishing forged "
                    "Merkle receipts; the audit plane must convict the "
                    "liar on both processes with identical bitfields "
                    "and exactly one fabric_distrust flight dump each")
    sp.add_argument("--fleet", action="store_true",
                    help="also run the fleet-observability smoke: two "
                    "workers, one h2d-throttled; the healthy peer's "
                    "/v1/fleet must name the throttled process (and its "
                    "h2d stage) as the fleet bottleneck")
    sp.add_argument("--announce", action="store_true",
                    help="also run the announce-plane smoke: concurrent "
                    "announces from multiple simulated swarms against "
                    "the sharded store; sampled replies well-formed, "
                    "shard counts reconcile")
    sp.add_argument("--slo", action="store_true",
                    help="also run the SLO-engine smoke: a FaultPlan "
                    "fail burst through a --slo bridge must burn the "
                    "availability budget, flip /v1/health ready→"
                    "degraded, fire exactly one slo_breach flight "
                    "dump, and recover")
    sp.add_argument("--swarm", action="store_true",
                    help="also run the swarm wire-plane smoke: a "
                    "throttled two-peer loopback download must be "
                    "attributed to the recv stage via /v1/pipeline, "
                    "/v1/swarm must report bounded per-peer telemetry, "
                    "and a driven snub storm must fire exactly one "
                    "flight dump")
    sp.add_argument("--lint", action="store_true",
                    help="also run the analysis-plane smoke: all four "
                    "static passes clean against the committed baseline")
    sp.add_argument("--scenario", metavar="NAMES",
                    help="also run bundled hostile-internet scenarios "
                    "(comma-separated names from scenario/library): each "
                    "runs twice against the real serve stack on a "
                    "virtual timeline; SLO verdict must pass and the "
                    "same-seed replay must be bit-identical")
    sp.add_argument("--seed", action="store_true",
                    help="also run the seeder-plane smoke: raw-wire "
                    "leechers against a real seeding client; every "
                    "piece must arrive bit-exact, /v1/swarm must carry "
                    "the serve sub-document, and the choke economics "
                    "must rotate the optimistic slot")
    sp.add_argument("--trace", action="store_true",
                    help="also run the observability smoke: traced "
                    "fault-injected run producing a span tree, latency "
                    "histograms, and flight-recorder dumps")
    sp.add_argument("--json", action="store_true",
                    help="emit a machine-readable JSON summary line")
    sp.set_defaults(fn=_cmd_doctor)

    sp = sub.add_parser(
        "top",
        help="live terminal view of the pipeline ledger from a running "
        "bridge (per-stage utilization + bottleneck verdict)",
    )
    sp.add_argument("--url", default="http://127.0.0.1:8421",
                    help="bridge base URL (default %(default)s)")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds (default %(default)s)")
    sp.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    sp.add_argument("--fleet", action="store_true",
                    help="render the swarm-wide fleet view (/v1/fleet: "
                    "straggler scoreboard + limiting process/stage) "
                    "instead of the local pipeline ledger")
    sp.add_argument("--history", action="store_true",
                    help="render the timeline view (/v1/timeline: "
                    "per-stage sparkline rows over the sample ring + "
                    "SLO burn/budget lines)")
    sp.add_argument("--swarm", action="store_true",
                    help="render the swarm wire-plane view (/v1/swarm: "
                    "per-peer scoreboard with state flags, pipeline "
                    "depth, block-RTT p99, snubs, overflow fold)")
    sp.set_defaults(fn=_cmd_top)

    sp = sub.add_parser(
        "replay",
        help="post-mortem replay of a dumped timeline (obs/timeline): "
        "the bottleneck attributor re-run over historical sample "
        "deltas — 'what was limiting at T-5m' after the process died",
    )
    sp.add_argument("file", help="a TORRENT_TPU_TIMELINE_DIR dump or a "
                    "saved GET /v1/timeline payload")
    sp.add_argument("--slo", default=None, metavar="SPEC",
                    help="also evaluate SLO objectives over the ring "
                    "(obs/slo spec, e.g. 'availability=0.999;integrity=on')")
    sp.add_argument("--intervals", type=int, default=12,
                    help="most-recent intervals to print (default %(default)s)")
    sp.add_argument("--json", action="store_true",
                    help="emit the full replay report as JSON")
    sp.set_defaults(fn=_cmd_replay)

    sp = sub.add_parser(
        "serve",
        help="long-running tracker deployment: sharded announce plane + "
        "DHT indexer crawl loop + /v1/health + /metrics in one command",
    )
    sp.add_argument("--http-port", type=int, default=8000)
    sp.add_argument("--udp-port", type=int, default=6969,
                    help="negative disables the UDP transport")
    sp.add_argument("--host", default="0.0.0.0")
    sp.add_argument("--interval", type=int, default=600)
    sp.add_argument("--shards", type=int, default=8)
    sp.add_argument("--dht-port", type=int, default=6881,
                    help="DHT indexer UDP port (negative disables)")
    sp.add_argument("--crawl-interval", type=float, default=300.0,
                    help="seconds between BEP 51 crawl steps")
    sp.add_argument("--slo", nargs="?", const=True, default=None,
                    metavar="SPEC",
                    help="arm the timeline sampler + SLO engine (no SPEC "
                    "= the default availability+integrity contract)")
    sp.add_argument("--timeline-interval", type=float, default=2.0)
    sp.set_defaults(fn=_cmd_serve)

    sp = sub.add_parser(
        "bench",
        help="unified bench rungs (smoke/v2/fabric/flagship): banked-"
        "schema records with the pipeline-ledger stage breakdown "
        "embedded, plus the trajectory comparator",
    )
    sp.add_argument("rung", nargs="?",
                    choices=("smoke", "e2e", "v2", "fabric", "flagship",
                             "controller", "announce", "swarm", "seed"))
    sp.add_argument("--smoke", action="store_true",
                    help="alias for the smoke rung (the CI spelling)")
    sp.add_argument("--mb", type=int, default=8,
                    help="smoke rung payload MiB (default %(default)s)")
    sp.add_argument("--piece-kb", type=int, default=256,
                    help="smoke rung piece KiB (default %(default)s)")
    sp.add_argument("--batch-target", type=int, default=32,
                    help="smoke rung scheduler launch target")
    sp.add_argument("--hasher", default="tpu", choices=("tpu", "cpu"),
                    help="e2e rung hash plane (default %(default)s)")
    sp.add_argument("--clients", type=int, default=8,
                    help="announce rung announcer threads")
    sp.add_argument("--swarms", type=int, default=32,
                    help="announce rung distinct info-hashes")
    sp.add_argument("--per-client", type=int, default=2000,
                    help="announce rung announces per client per rep")
    sp.add_argument("--shards", type=int, default=8,
                    help="announce rung store shard count")
    sp.add_argument("--numwant", type=int, default=30,
                    help="announce rung peers requested per announce")
    sp.add_argument("--leechers", type=int, default=64,
                    help="seed rung concurrent loopback leechers "
                    "(default %(default)s)")
    sp.add_argument("--timeout", type=float, default=None,
                    help="device-rung subprocess timeout seconds")
    sp.add_argument("--out", default=None, help="also write the record here")
    sp.add_argument("--record", default=None, metavar="FILE",
                    help="skip the run; compare/bank this record instead")
    sp.add_argument("--compare", action="store_true",
                    help="gate the record against the banked trajectory "
                    "(unarmed when no like-for-like record is banked)")
    sp.add_argument("--trajectory", default=None, metavar="FILE",
                    help="trajectory file (default BENCH_trajectory.json)")
    sp.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed fractional regression (default %(default)s)")
    sp.add_argument("--report-only", action="store_true",
                    help="comparator reports but never fails the run")
    sp.add_argument("--bank", action="store_true",
                    help="append the record to the trajectory (self-banking)")
    sp.set_defaults(fn=_cmd_bench)

    sp = sub.add_parser("tracker", help="run the in-memory tracker server")
    sp.add_argument("--http-port", type=int, default=8080)
    # same default as the standalone torrent-tracker entrypoint; negative
    # disables UDP
    sp.add_argument("--udp-port", type=int, default=6969)
    sp.add_argument("--interval", type=int, default=600)
    sp.add_argument("--state-file", help="persist swarm state across restarts")
    sp.add_argument("--shards", type=int, default=0,
                    help="run the sharded announce plane with N shards "
                    "(batched announces, O(numwant) sampling, per-shard "
                    "TTL sweeps, /metrics route; 0 = legacy single-dict "
                    "tracker)")
    sp.set_defaults(fn=_cmd_tracker)

    sp = sub.add_parser("bridge", help="run the TPU hash-plane HTTP bridge")
    sp.add_argument("--port", type=int, default=8421)
    sp.add_argument("--hasher", choices=("cpu", "tpu"), default="tpu")
    # continuous-batching scheduler knobs (torrent_tpu/sched): launch
    # fill target, deadline for stranded small requests, and the
    # admission-control byte bounds that turn overload into 429s
    sp.add_argument("--batch-target", type=int, default=256,
                    help="pieces per device launch the scheduler fills to")
    sp.add_argument("--flush-deadline-ms", type=float, default=20.0,
                    help="max ms a queued piece waits before a partial flush")
    sp.add_argument("--max-queue-mb", type=int, default=256,
                    help="global queued-bytes bound (requests shed with 429 beyond)")
    sp.add_argument("--tenant-max-mb", type=int, default=128,
                    help="per-tenant queued-bytes bound")
    sp.add_argument("--autopilot", action="store_true",
                    help="arm the scheduler autopilot: adaptive lane "
                    "targets/deadlines, limiting-stage admission budgets, "
                    "hysteresis-guarded backend steering (GET /v1/control)")
    sp.add_argument("--autopilot-interval", type=float, default=1.0,
                    metavar="S",
                    help="seconds between controller decisions "
                    "(default %(default)s)")
    sp.add_argument("--slo", nargs="?", const=True, default=None,
                    metavar="SPEC",
                    help="arm the timeline sampler + SLO engine "
                    "(obs/slo spec; no SPEC = the default availability+"
                    "integrity contract). Serves /v1/timeline, /v1/slo "
                    "and the torrent_tpu_slo_*/timeline_* series; "
                    "/v1/health reflects breaches")
    sp.add_argument("--timeline-interval", type=float, default=1.0,
                    metavar="S",
                    help="seconds between timeline samples when --slo "
                    "is armed (default %(default)s)")
    sp.add_argument("--fault-plan", default=None, metavar="SPEC",
                    help="inject deterministic hash-plane faults "
                    "(sched/faults.py spec; requires --dev or TORRENT_TPU_DEV=1)")
    sp.add_argument("--dev", action="store_true",
                    help="dev/test mode: unlocks chaos knobs like --fault-plan")
    sp.set_defaults(fn=_cmd_bridge)

    return p


def main(argv: list[str] | None = None) -> int:
    import os

    plat = os.environ.get("TORRENT_TPU_PLATFORM")
    if plat:
        # Some images pin jax_platforms via sitecustomize (so the
        # JAX_PLATFORMS env var is overridden before user code runs);
        # jax.config.update after import wins. Lets an operator force
        # e.g. cpu when the device tunnel is down.
        import jax

        jax.config.update("jax_platforms", plat)
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except KeyboardInterrupt:  # pragma: no cover - interactive
        return 130


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
