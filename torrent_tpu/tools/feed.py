"""BEP 36 torrent RSS/Atom feeds: subscribe and auto-add new entries.

The reference has no feed support (its README's scope ends at the wire
protocols). Real clients grow one because it's how long-running seeds
track a publisher: poll the feed, fetch each new entry's .torrent, add
it. This module is that loop, built on the session layer:

- :func:`parse_feed` — RSS 2.0 (``<item><enclosure url .../>``,
  ``<link>`` fallback) and Atom (``<entry><link href .../>``), plus the
  BEP 36 convention of magnet links in either slot. Untrusted XML: any
  DOCTYPE is rejected outright (entity-expansion bombs), and only
  http(s)/magnet URLs survive.
- :class:`FeedPoller` — periodic poll through the proxy-aware tracker
  HTTP client (size-capped while streaming), dedup by entry URL and by
  infohash after parsing, ``Client.add``/``add_magnet`` for new items.

CLI: ``torrent-tpu feed URL DIR [--interval N] [--once]``.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from torrent_tpu.utils.log import get_logger

log = get_logger("tools.feed")

MAX_FEED_BYTES = 4 << 20  # a feed document is text; 4 MiB is generous
MAX_TORRENT_BYTES = 16 << 20


class FeedPermanentRefusal(Exception):
    """An entry that can NEVER be accepted (e.g. a magnet under the
    signature gate — BEP 35 signatures live at the torrent root, so no
    future publisher action can make the same magnet pass). poll_once
    marks these seen: re-refusing them every poll is pure churn."""


class FeedError(Exception):
    pass


@dataclass(frozen=True)
class FeedItem:
    title: str
    url: str  # http(s) .torrent URL or a magnet URI


def _clean_url(url: str | None) -> str | None:
    if not url:
        return None
    url = url.strip()
    scheme = url.split(":", 1)[0].lower() if ":" in url else ""
    if scheme in ("http", "https", "magnet"):
        return url
    return None  # file://, ftp://, javascript:, ... are hostile here


def parse_feed(data: bytes) -> list[FeedItem]:
    """Feed document → ordered items (first = newest, as published).

    Raises FeedError on undecodable/hostile documents; unknown elements
    are ignored (feeds are messy in the wild).
    """
    if b"<!DOCTYPE" in data[:4096] or b"<!ENTITY" in data:
        # internal entity expansion is the classic XML bomb and no real
        # feed needs a DTD — refuse rather than parse carefully
        raise FeedError("feed contains a DOCTYPE/ENTITY declaration; refusing")
    import xml.etree.ElementTree as ET

    try:
        root = ET.fromstring(data)
    except ET.ParseError as e:
        raise FeedError(f"feed is not well-formed XML: {e}") from e

    def tag(el) -> str:
        return el.tag.rsplit("}", 1)[-1].lower()  # strip xmlns

    items: list[FeedItem] = []
    # RSS 2.0: rss > channel > item
    for item in root.iter():
        if tag(item) != "item":
            continue
        title, url = "", None
        for child in item:
            t = tag(child)
            if t == "title" and child.text:
                title = child.text.strip()
            elif t == "enclosure":
                url = _clean_url(child.get("url")) or url
        if url is None:  # <link> fallback, lower priority than enclosure
            for child in item:
                if tag(child) == "link" and child.text:
                    url = _clean_url(child.text)
                    if url:
                        break
        if url:
            items.append(FeedItem(title=title, url=url))
    if items:
        return items
    # Atom: feed > entry > link[@href]
    for entry in root.iter():
        if tag(entry) != "entry":
            continue
        title, url = "", None
        for child in entry:
            t = tag(child)
            if t == "title" and child.text:
                title = child.text.strip()
            elif t == "link":
                # prefer rel="enclosure"; plain links as fallback
                cand = _clean_url(child.get("href"))
                if cand and (url is None or child.get("rel") == "enclosure"):
                    url = cand
        if url:
            items.append(FeedItem(title=title, url=url))
    return items


class FeedPoller:
    """Poll one feed and add its new entries to a Client.

    ``seen`` carries across polls (and can be pre-seeded by the caller
    to resume a subscription without re-adding history). Every added
    torrent is also remembered by infohash, so a feed that rotates its
    URLs cannot re-add the same content.
    """

    def __init__(
        self,
        client,
        url: str,
        download_dir: str,
        interval: float = 300.0,
        seen: set[str] | None = None,
        require_signed: tuple[str, bytes] | None = None,
    ):
        self.client = client
        self.url = url
        self.download_dir = download_dir
        self.interval = interval
        # (signer, 32B Ed25519 key): every fetched .torrent must carry a
        # valid BEP 35 signature or it is skipped — the feed auto-add is
        # the highest-risk ingestion path (whatever XML says, we fetch
        # and run). Magnet entries are refused under the gate: BEP 9
        # metadata cannot carry root signatures.
        self.require_signed = require_signed
        self.seen: set[str] = seen if seen is not None else set()
        # infohashes ride the same persisted set as "ih:<hex>" entries,
        # so a publisher rotating entry URLs (signed/expiring links)
        # can't re-add content across process restarts either
        self._seen_hashes: set[bytes] = set()
        for s in self.seen:
            if s.startswith("ih:"):
                try:
                    self._seen_hashes.add(bytes.fromhex(s[3:]))
                except ValueError:
                    pass
        self._task: asyncio.Task | None = None

    async def poll_once(self) -> list:
        """One poll: fetch, parse, add new items; returns added torrents."""
        from torrent_tpu.net.tracker import _http_get

        raw = await _http_get(
            self.url,
            timeout=30,
            proxy=self.client.proxy,
            max_bytes=MAX_FEED_BYTES,
        )
        added = []
        for item in parse_feed(raw):
            if item.url in self.seen:
                continue
            try:
                t = await self._add_item(item)
            except FeedPermanentRefusal as e:
                # marked seen: this entry can never be accepted, so one
                # warning is all it gets (not one per poll forever)
                log.warning("feed %s: %r refused permanently: %s",
                            self.url, item.title, e)
                self.seen.add(item.url)
                continue
            except Exception as e:
                # NOT marked seen: a transiently-503ing download URL gets
                # retried on the next poll instead of being dropped
                # forever (an unsigned .torrent may also be SIGNED later
                # — root signatures don't change its URL or infohash)
                log.warning("feed %s: adding %r failed: %s", self.url, item.title, e)
                continue
            self.seen.add(item.url)
            if t is not None:
                self._remember_hash(t.metainfo.info_hash)
                added.append(t)
        return added

    def _remember_hash(self, ih: bytes) -> None:
        self._seen_hashes.add(ih)
        self.seen.add("ih:" + ih.hex())

    async def _add_item(self, item: FeedItem):
        if item.url.startswith("magnet:"):
            if self.require_signed is not None:
                raise FeedPermanentRefusal(
                    f"{item.url!r}: magnet entries cannot satisfy the "
                    f"signature gate (no root signatures in BEP 9 metadata)"
                )
            return await self.client.add_magnet(item.url, self.download_dir)
        from torrent_tpu.net.tracker import _http_get

        raw = await _http_get(
            item.url,
            timeout=30,
            proxy=self.client.proxy,
            max_bytes=MAX_TORRENT_BYTES,
        )
        if self.require_signed is not None:
            from torrent_tpu.codec import signing

            try:
                signing.ensure_signed(raw, *self.require_signed)
            except ValueError as e:
                raise FeedError(f"{item.url} refused: {e}") from e
        from torrent_tpu.codec.metainfo import parse_any_metainfo

        parsed = parse_any_metainfo(raw)
        if parsed is None:
            raise FeedError(f"{item.url} did not serve a valid .torrent")
        meta, ih = parsed
        if ih in self._seen_hashes or ih in self.client.torrents:
            self._remember_hash(ih)  # persist the rotated-URL knowledge
            return None  # same content under a rotated URL
        return await self.client.add(meta, self.download_dir)

    def start(self) -> None:
        """Spawn the periodic poll loop (errors are logged, never fatal:
        a feed that 500s for an hour resumes on the next tick)."""
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                added = await self.poll_once()
                if added:
                    log.info("feed %s: added %d new torrents", self.url, len(added))
            except Exception as e:
                log.warning("feed %s: poll failed: %s", self.url, e)
            await asyncio.sleep(self.interval)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
