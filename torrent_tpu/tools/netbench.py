"""Reproducible swarm-throughput benchmarks on loopback.

The numbers quoted in PARITY.md / ARCHITECTURE.md for the session layer
(single-leech TCP, single-leech uTP, N-leech fanout) come from here.
Everything runs real clients over real sockets against the in-memory
tracker — the only synthetic part is MemoryStorage, so the measurement
isolates protocol + scheduler + transport cost from disk.

Usage::

    python -m torrent_tpu.tools.netbench [--mode single|fanout|utp|raw-utp]
        [--mb 256] [--piece-kb 256] [--leeches 8] [--json]

One line per run; --json emits machine-readable records. Run on an
otherwise-idle machine: every client shares the host's cores, so a
loaded box understates (never overstates) the numbers.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time


async def _swarm(total: int, piece: int, n_leech: int, utp: bool) -> dict:
    import numpy as np

    from torrent_tpu.codec.metainfo import parse_metainfo
    from torrent_tpu.session.client import Client, ClientConfig
    from torrent_tpu.storage.storage import MemoryStorage, Storage

    # the test harness's tracker + torrent builders are intentionally
    # reused: the bench must measure the same stack the suite proves
    # (resolved relative to this file so `python -m
    # torrent_tpu.tools.netbench` works from any working directory)
    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", "tests"),
    )
    from test_session import build_torrent_bytes, fast_config, start_tracker

    rng = np.random.default_rng(7)
    payload = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
    server, pump, announce_url = await start_tracker()
    meta = parse_metainfo(
        build_torrent_bytes(payload, piece, announce_url.encode(), name=b"bench.bin")
    )

    def mk() -> Client:
        c = Client(
            ClientConfig(host="127.0.0.1", enable_upnp=False, enable_utp=utp)
        )
        c.config.torrent = fast_config(
            unchoke_slots=max(4, n_leech)
        )
        return c

    seed = mk()
    await seed.start()
    ss = Storage(MemoryStorage(), meta.info)
    for off in range(0, total, 1 << 20):
        ss.set(off, payload[off : off + (1 << 20)])
    await seed.add(meta, ss)
    leeches = []
    for _ in range(n_leech):
        c = mk()
        await c.start()
        leeches.append(c)
    t0 = time.perf_counter()
    torrents = [
        await c.add(meta, Storage(MemoryStorage(), meta.info)) for c in leeches
    ]
    await asyncio.gather(
        *(asyncio.wait_for(t.on_complete.wait(), 600) for t in torrents)
    )
    secs = time.perf_counter() - t0
    for c in leeches:
        await c.close()
    await seed.close()
    server.close()
    pump.cancel()
    return {
        "metric": (
            f"swarm_{'utp' if utp else 'tcp'}_{n_leech}leech_mib_s"
        ),
        "value": round(total * n_leech / 2**20 / secs, 1),
        "unit": "MiB/s aggregate" if n_leech > 1 else "MiB/s",
        "seconds": round(secs, 2),
        "total_mb": total >> 20,
        "piece_kb": piece >> 10,
        "leeches": n_leech,
    }


async def _raw_utp(total: int) -> dict:
    """Raw uTP stream throughput (no session layer): endpoint to
    endpoint over loopback, jumbo-MTU rung active."""
    import numpy as np

    from torrent_tpu.net import utp

    loop = asyncio.get_running_loop()
    got = bytearray()
    done = asyncio.Event()

    async def consume(r, w):
        while True:
            chunk = await r.read(1 << 16)
            if not chunk:
                break
            got.extend(chunk)
            if len(got) >= total:
                break
        w.close()
        done.set()

    t_b, ep_b = await loop.create_datagram_endpoint(
        lambda: utp.UtpEndpoint(consume), local_addr=("127.0.0.1", 0)
    )
    t_a, ep_a = await loop.create_datagram_endpoint(
        lambda: utp.UtpEndpoint(None), local_addr=("127.0.0.1", 0)
    )
    payload = np.random.default_rng(1).integers(
        0, 256, total, dtype=np.uint8
    ).tobytes()
    r, w = await ep_a.dial("127.0.0.1", t_b.get_extra_info("sockname")[1])
    t0 = time.perf_counter()
    for off in range(0, total, 1 << 16):
        w.write(payload[off : off + (1 << 16)])
        await w.drain()
    w.close()
    await asyncio.wait_for(done.wait(), 300)
    secs = time.perf_counter() - t0
    assert bytes(got[:total]) == payload, "corrupt transfer"
    t_a.close()
    t_b.close()
    return {
        "metric": "raw_utp_loopback_mib_s",
        "value": round(total / 2**20 / secs, 1),
        "unit": "MiB/s",
        "seconds": round(secs, 2),
        "total_mb": total >> 20,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="netbench", description=__doc__)
    ap.add_argument(
        "--mode",
        choices=("single", "fanout", "utp", "raw-utp"),
        default="single",
    )
    ap.add_argument("--mb", type=int, default=256)
    ap.add_argument("--piece-kb", type=int, default=256)
    ap.add_argument("--leeches", type=int, default=8)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    total = args.mb << 20
    piece = args.piece_kb << 10
    if args.mode == "single":
        rec = asyncio.run(_swarm(total, piece, 1, utp=False))
    elif args.mode == "fanout":
        rec = asyncio.run(_swarm(total, piece, args.leeches, utp=False))
    elif args.mode == "utp":
        rec = asyncio.run(_swarm(total, piece, 1, utp=True))
    else:
        rec = asyncio.run(_raw_utp(total))
    if args.json:
        print(json.dumps(rec))
    else:
        print(f"{rec['metric']}: {rec['value']} {rec['unit']} ({rec['seconds']}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
