"""`torrent-tpu serve` — the long-running tracker deployment recipe.

One command wires the production announce plane (PR 12) into a
deployable service, closing the ROADMAP's "long-running deployment
recipe" remainder:

* the **sharded tracker** (``server/shard.py``): HTTP + UDP announce
  transports feeding the sharded swarm store through the batched pump,
* the **DHT indexer** (``net/indexer.py``): a DHT node harvesting
  ``get_peers``/``announce_peer`` passively and walking BEP 51 samples
  on a bounded budget, seeding the store with persistent-tracker
  semantics (magnet-only swarms answerable with no ``.torrent``),
* ``GET /v1/health`` on the tracker listener: liveness + readiness
  (pump ticking, sampler alive, SLO breaches) for a real load balancer,
* ``GET /metrics``: ``torrent_tpu_tracker_*`` + announce-latency
  histograms, plus the timeline/SLO series when ``--slo`` is armed,
* optionally (``--slo``) the **timeline sampler + SLO engine**
  (``obs/timeline`` + ``obs/slo``): periodic obs samples with tracker
  facts, error-budget burn rates, a ``slo_breach`` flight dump per
  breach transition, and post-mortem dumps to
  ``TORRENT_TPU_TIMELINE_DIR``.

Run it under any supervisor — see the README quickstart for systemd
and container examples. Everything binds the ``--host`` you give it
(default all interfaces: a tracker exists to be announced to).
"""

from __future__ import annotations

import asyncio

from torrent_tpu.utils.log import get_logger

log = get_logger("tools.serve")

__all__ = ["ServiceHandle", "start_service", "main"]


class ServiceHandle:
    """A running deployment: the tracker transports + pump, the DHT
    node + indexer crawl loop, and (when armed) the timeline/SLO tier.
    ``close()`` tears everything down in reverse dependency order."""

    def __init__(self):
        self.server = None  # TrackerServer (http_port/udp_port)
        self.pump_task: asyncio.Task | None = None
        self.store = None
        self.dht = None
        self.indexer = None
        self.crawl_task: asyncio.Task | None = None
        self.timeline = None
        self.sampler = None
        self.slo_engine = None

    @property
    def http_port(self):
        return self.server.http_port if self.server else None

    async def close(self) -> None:
        if self.sampler is not None:
            await asyncio.to_thread(self.sampler.stop)
            from torrent_tpu.obs import slo as _slo

            # only release the slot if it is still ours (a later server
            # in the same process may have armed its own engine)
            _slo.disarm(self.slo_engine)
        for task in (self.crawl_task, self.pump_task):
            if task is not None and not task.done():
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        if self.dht is not None:
            self.dht.close()
        if self.server is not None:
            self.server.close()


async def start_service(
    http_port: int = 8000,
    udp_port: int | None = 6969,
    host: str = "0.0.0.0",
    interval: int = 600,
    shards: int = 8,
    dht_port: int | None = 6881,
    crawl_interval: float = 300.0,
    slo=None,
    timeline_interval_s: float = 2.0,
) -> ServiceHandle:
    """Wire and start the whole deployment. ``dht_port=None`` disables
    the indexer (tracker-only mode); ``slo`` arms the timeline/SLO tier
    (True = the default contract, or an objective spec string)."""
    from torrent_tpu.server.shard import (
        PUMP_MAX_AGE_S,
        ShardedSwarmStore,
        run_sharded_tracker,
    )
    from torrent_tpu.server.tracker import ServeOptions

    handle = ServiceHandle()
    handle.store = ShardedSwarmStore(n_shards=shards, interval=interval)

    if dht_port is not None:
        from torrent_tpu.net.dht import DHTNode
        from torrent_tpu.net.indexer import DhtIndexer

        handle.dht = await DHTNode(host=host, port=dht_port).start()
        handle.indexer = DhtIndexer(handle.dht, handle.store)
        handle.crawl_task = asyncio.create_task(
            handle.indexer.crawl(interval=crawl_interval)
        )

    handle.server, handle.pump_task = await run_sharded_tracker(
        ServeOptions(http_port=http_port, udp_port=udp_port, host=host,
                     interval=interval),
        store=handle.store,
        indexer=handle.indexer,
    )
    pump_state = handle.pump_task.pump_state

    if slo:
        import time as _time

        from torrent_tpu.obs import slo as _slo
        from torrent_tpu.obs.slo import (
            DEFAULT_SLO_SPEC,
            SloEngine,
            build_health,
        )
        from torrent_tpu.obs.timeline import Timeline, TimelineSampler

        handle.slo_engine = _slo.arm(
            SloEngine(DEFAULT_SLO_SPEC if slo is True else slo)
        )
        handle.timeline = Timeline()

        def _tracker_facts() -> dict:
            snap = handle.store.metrics_snapshot()
            return {
                "announces": snap.get("announces", 0),
                "peers": snap.get("peers", 0),
                "swarms": snap.get("swarms", 0),
            }

        handle.sampler = TimelineSampler(
            handle.timeline,
            interval_s=timeline_interval_s,
            sources={"tracker": _tracker_facts},
            on_sample=handle.slo_engine.observe,
            on_sample_tail=handle.slo_engine.long_samples,
        ).start()

        # /v1/health now also reflects sampler liveness + SLO breaches;
        # /metrics carries the timeline + SLO series
        def _health() -> dict:
            return build_health(
                pump_age_s=_time.monotonic() - pump_state["tick"],
                pump_max_age_s=PUMP_MAX_AGE_S,
                sampler_alive=handle.sampler.alive,
                slo_report=handle.slo_engine.report(),
            )

        handle.server.health_provider = _health
        base_metrics = handle.server.metrics_provider

        def _metrics() -> str:
            from torrent_tpu.utils.metrics import (
                render_slo_metrics,
                render_timeline_metrics,
            )

            tl = handle.timeline.stats()  # counters only, no ring copy
            tl["sampler_alive"] = handle.sampler.alive
            return (
                base_metrics()
                + render_timeline_metrics(tl)
                + render_slo_metrics(handle.slo_engine.report())
            )

        handle.server.metrics_provider = _metrics
    return handle


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="torrent-tpu serve", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--http-port", type=int, default=8000)
    ap.add_argument(
        "--udp-port", type=int, default=6969,
        help="negative value disables the UDP transport",
    )
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument(
        "--interval", type=int, default=600,
        help="announce interval handed to clients (default %(default)s)",
    )
    ap.add_argument(
        "--shards", type=int, default=8,
        help="swarm-store shards (default %(default)s)",
    )
    ap.add_argument(
        "--dht-port", type=int, default=6881,
        help="DHT indexer UDP port (negative disables the indexer)",
    )
    ap.add_argument(
        "--crawl-interval", type=float, default=300.0,
        help="seconds between BEP 51 indexer crawl steps (default %(default)s)",
    )
    ap.add_argument(
        "--slo", nargs="?", const=True, default=None, metavar="SPEC",
        help="arm the timeline sampler + SLO engine (obs/slo spec, e.g. "
        "'availability=0.999;integrity=on'; no SPEC = the default "
        "contract). /v1/health reflects breaches; /metrics gains "
        "torrent_tpu_slo_*/timeline_* series",
    )
    ap.add_argument(
        "--timeline-interval", type=float, default=2.0, metavar="S",
        help="seconds between timeline samples when --slo is armed",
    )
    args = ap.parse_args(argv)

    async def go() -> int:
        handle = await start_service(
            http_port=args.http_port,
            udp_port=args.udp_port if args.udp_port >= 0 else None,
            host=args.host,
            interval=args.interval,
            shards=args.shards,
            dht_port=args.dht_port if args.dht_port >= 0 else None,
            crawl_interval=args.crawl_interval,
            slo=args.slo,
            timeline_interval_s=args.timeline_interval,
        )
        print(
            f"torrent-tpu serve: tracker http={handle.server.http_port} "
            f"udp={handle.server.udp_port} shards={args.shards} "
            f"dht={handle.dht.port if handle.dht else 'off'} "
            f"slo={'armed' if handle.slo_engine else 'off'}"
        )
        print(
            f"  health: http://{args.host}:{handle.server.http_port}/v1/health"
            f"  metrics: http://{args.host}:{handle.server.http_port}/metrics"
        )
        try:
            await handle.pump_task
        except asyncio.CancelledError:
            pass
        finally:
            await handle.close()
        return 0

    try:
        return asyncio.run(go())
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":  # pragma: no cover - manual entrypoint
    raise SystemExit(main())
