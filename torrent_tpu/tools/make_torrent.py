""".torrent authoring (ref L7: tools/make_torrent.ts, 250 LoC).

The reference's only compute-bound path: read every piece, SHA1 it, emit
the metainfo (tools/make_torrent.ts:115-174). Differences by design:

- **Batched hashing**: pieces accumulate into batches and hash through
  the device hash plane (``TPUVerifier.hash_pieces``) or hashlib
  (``hasher='cpu'``) — the reference pipelines per-piece WebCrypto
  promises (tools/make_torrent.ts:96-111); we pipeline whole batches.
- Same piece-length heuristic: power of two, 32 KiB–1 MiB, targeting
  ~1000 pieces (tools/make_torrent.ts:18-33).
- Multi-file pieces span file boundaries via a carry buffer
  (tools/make_torrent.ts:62-113) — here a single streaming reader over
  the concatenated file list.
"""

from __future__ import annotations

import os
import sys
import time
from dataclasses import dataclass
from typing import Callable, Iterator

from torrent_tpu.codec.bencode import bencode

MIN_PIECE_LEN = 32 * 1024
MAX_PIECE_LEN = 1024 * 1024
TARGET_PIECES = 1000


def choose_piece_length(total_size: int) -> int:
    """Power of 2 in [32 KiB, 1 MiB] targeting ~1000 pieces
    (tools/make_torrent.ts:18-33)."""
    target = max(1, total_size // TARGET_PIECES)
    plen = MIN_PIECE_LEN
    while plen < target and plen < MAX_PIECE_LEN:
        plen *= 2
    return plen


def collect_files(root: str) -> list[tuple[str, int]]:
    """Deterministic walk → [(relpath, size)] (tools/make_torrent.ts:35-60)."""
    out: list[tuple[str, int]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            out.append((os.path.relpath(full, root), os.path.getsize(full)))
    return out


def _iter_pieces(paths: list[str], piece_len: int, pad: bool = False) -> Iterator[bytes]:
    """Stream fixed-size pieces across file boundaries (the carry-buffer
    loop of tools/make_torrent.ts:62-113, as a generator).

    With ``pad``, zero bytes fill to the next piece boundary after every
    file but the last (BEP 47): the zeros are hashed into the piece
    stream exactly as a downloader's virtual pad spans will replay them.
    """
    carry = bytearray()
    for i, path in enumerate(paths):
        with open(path, "rb") as f:
            while True:
                chunk = f.read(max(piece_len, 1 << 20))
                if not chunk:
                    break
                carry += chunk
                while len(carry) >= piece_len:
                    yield bytes(carry[:piece_len])
                    del carry[:piece_len]
        if pad and i < len(paths) - 1 and len(carry) % piece_len:
            carry += bytes(piece_len - len(carry) % piece_len)
            while len(carry) >= piece_len:
                yield bytes(carry[:piece_len])
                del carry[:piece_len]
    if carry:
        yield bytes(carry)


@dataclass
class _Hasher:
    """Batched piece hasher: hashlib or the TPU hash plane."""

    hasher: str = "cpu"
    piece_length: int = MIN_PIECE_LEN
    batch_size: int = 256
    _verifier: object = None

    def digests(self, pieces: Iterator[bytes], progress: Callable | None = None) -> list[bytes]:
        if self.hasher == "cpu":
            import hashlib

            out = []
            for i, p in enumerate(pieces):
                out.append(hashlib.sha1(p).digest())
                if progress and (i + 1) % 64 == 0:
                    progress(i + 1)
            if progress and out:
                progress(len(out))  # final count (not a multiple of 64)
            return out
        if self.hasher == "tpu":
            from torrent_tpu.models.verifier import TPUVerifier

            if self._verifier is None:
                self._verifier = TPUVerifier(
                    piece_length=self.piece_length, batch_size=self.batch_size
                )
            out = []
            batch: list[bytes] = []
            for p in pieces:
                batch.append(p)
                if len(batch) >= self.batch_size:
                    out.extend(self._verifier.hash_pieces(batch))
                    batch.clear()
                    if progress:
                        progress(len(out))
            if batch:
                out.extend(self._verifier.hash_pieces(batch))
            if progress and out:
                progress(len(out))
            return out
        raise ValueError(f"unknown hasher {self.hasher!r}")


def make_torrent(
    path: str,
    tracker: str,
    comment: str | None = None,
    piece_length: int | None = None,
    hasher: str = "cpu",
    progress: Callable | None = None,
    announce_list: list[list[str]] | None = None,
    private: bool = False,
    web_seeds: list[str] | None = None,
    pad_files: bool = False,
    similar: list[bytes] | None = None,
    collections: list[str] | None = None,
    update_url: str | None = None,
) -> bytes:
    """Author a .torrent for a file or directory (tools/make_torrent.ts:115).

    Returns the bencoded metainfo bytes; caller writes them where it wants.
    ``announce_list`` adds BEP 12 tiers; ``private`` sets BEP 27's flag
    (changes the infohash — clients then skip DHT/PEX); ``web_seeds``
    adds a BEP 19 ``url-list``; ``pad_files`` inserts BEP 47 pad entries
    so every file after the first starts on a piece boundary (single-GET
    webseed ranges, per-file piece reuse — multi-file only); ``similar``
    (infohashes) and ``collections`` (group names) are BEP 38 hints that
    let downloaders reuse identical local files from related torrents —
    written INSIDE the info dict so the hints are infohash-bound.
    """
    path = os.fspath(path)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    is_dir = os.path.isdir(path)
    name = os.path.basename(os.path.abspath(path))

    if is_dir:
        files = collect_files(path)
        if not files:
            raise ValueError(f"directory {path!r} contains no files")
        total = sum(size for _, size in files)
        abs_paths = [os.path.join(path, rel) for rel, _ in files]
    else:
        total = os.path.getsize(path)
        abs_paths = [path]

    plen = piece_length or choose_piece_length(total)
    pad = bool(pad_files and is_dir and len(abs_paths) > 1)
    hasher_obj = _Hasher(hasher=hasher, piece_length=plen)
    digests = hasher_obj.digests(_iter_pieces(abs_paths, plen, pad=pad), progress)

    info: dict = {
        b"name": name.encode("utf-8"),
        b"piece length": plen,
        b"pieces": b"".join(digests),
    }
    if is_dir:
        entries = []
        for i, (rel, size) in enumerate(files):
            entries.append(
                {b"length": size, b"path": [c.encode("utf-8") for c in rel.split(os.sep)]}
            )
            short = size % plen
            if pad and i < len(files) - 1 and short:
                # BEP 47: an attr-p entry downloaders virtualize as zeros
                pad_len = plen - short
                entries.append(
                    {
                        b"attr": b"p",
                        b"length": pad_len,
                        b"path": [b".pad", str(pad_len).encode()],
                    }
                )
        info[b"files"] = entries
    else:
        info[b"length"] = total

    if private:
        info[b"private"] = 1  # BEP 27 — inside info: part of the infohash
    if similar:
        for h in similar:
            if not isinstance(h, bytes) or len(h) not in (20, 32):
                raise ValueError("similar entries must be 20- or 32-byte infohashes")
        info[b"similar"] = list(similar)  # BEP 38
    if collections:
        info[b"collections"] = [c.encode("utf-8") for c in collections]  # BEP 38
    if update_url:
        info[b"update-url"] = update_url.encode("utf-8")  # BEP 39

    top: dict = {b"announce": tracker.encode("utf-8"), b"info": info}
    if announce_list:
        top[b"announce-list"] = [
            [t.encode("utf-8") for t in tier] for tier in announce_list
        ]
    if web_seeds:
        top[b"url-list"] = [u.encode("utf-8") for u in web_seeds]  # BEP 19
    if comment:
        top[b"comment"] = comment.encode("utf-8")
    top[b"creation date"] = int(time.time())
    top[b"created by"] = b"torrent-tpu/0.1"
    return bencode(top)


def main(argv: list[str] | None = None) -> int:  # pragma: no cover - CLI shell
    """argv CLI with a \\r progress line (tools/make_torrent.ts:190-250)."""
    import argparse

    parser = argparse.ArgumentParser(prog="make_torrent", description=__doc__)
    parser.add_argument("path", help="file or directory to share")
    parser.add_argument("tracker", help="announce URL")
    parser.add_argument("-o", "--output", help="output .torrent path")
    parser.add_argument("-c", "--comment")
    parser.add_argument("--piece-length", type=int)
    parser.add_argument("--hasher", choices=("cpu", "tpu"), default="cpu")
    args = parser.parse_args(argv)

    def progress(n):
        sys.stderr.write(f"\rhashed {n} pieces...")
        sys.stderr.flush()

    data = make_torrent(
        args.path,
        args.tracker,
        comment=args.comment,
        piece_length=args.piece_length,
        hasher=args.hasher,
        progress=progress,
    )
    out = args.output or (os.path.basename(os.path.abspath(args.path)) + ".torrent")
    with open(out, "wb") as f:
        f.write(data)
    sys.stderr.write(f"\rwrote {out} ({len(data)} bytes)\n")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
