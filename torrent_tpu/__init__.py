"""torrent_tpu — a TPU-native BitTorrent framework.

A from-scratch re-design of the capabilities of rclarey/torrent (a Deno
BitTorrent client + tracker library) as a Python/JAX framework whose hash
plane — piece SHA1 verification and authoring — runs batched on TPU via
JAX/Pallas, vmapped over pieces and sharded over a device mesh.

Layer map (mirrors reference layers, re-designed TPU-first; see SURVEY.md):

- ``torrent_tpu.utils``    — byte helpers, timeouts, logging        (ref L0)
- ``torrent_tpu.codec``    — bencode, validators, metainfo          (ref L1/L2)
- ``torrent_tpu.storage``  — piece math, pluggable storage          (ref L5)
- ``torrent_tpu.ops``      — SHA1 kernels: pure-JAX + Pallas TPU    (new)
- ``torrent_tpu.parallel`` — mesh/sharding + batched verify plane   (new)
- ``torrent_tpu.models``   — the flagship ``TPUVerifier`` pipeline  (new)
- ``torrent_tpu.net``      — tracker client, peer wire protocol     (ref L3a/L4)
- ``torrent_tpu.server``   — tracker server + in-memory tracker     (ref L3b)
- ``torrent_tpu.session``  — Torrent/Client session runtime         (ref L6)
- ``torrent_tpu.bridge``   — localhost HTTP bridge to the verifier  (new)
- ``torrent_tpu.tools``    — make_torrent authoring CLI             (ref L7)

(Empty subpackages in this tree are landing in build order — SURVEY.md §7.)
"""

__version__ = "0.3.0"

# Public API surface. The reference's mod.ts exports only codec + tracker
# (mod.ts:1-3, SURVEY §1 note); here the session layer is first-class.
from torrent_tpu.codec.bencode import bencode, bdecode, BencodeError
from torrent_tpu.codec.metainfo import parse_metainfo, Metainfo, InfoDict, FileEntry
from torrent_tpu.net.tracker import announce, scrape, TrackerError
from torrent_tpu.net.types import AnnounceEvent, AnnounceInfo, AnnounceResponse
from torrent_tpu.session.client import Client, ClientConfig
from torrent_tpu.session.torrent import Torrent, TorrentConfig, TorrentState
from torrent_tpu.storage.storage import Storage, StorageMethod, FsStorage, MemoryStorage
from torrent_tpu.parallel.verify import verify_pieces
from torrent_tpu.tools.make_torrent import make_torrent
from torrent_tpu.codec.magnet import Magnet, parse_magnet
from torrent_tpu.codec.metainfo_v2 import MetainfoV2, InfoDictV2, V2File, parse_metainfo_v2
from torrent_tpu.session.v2 import V2SessionMeta, v2_session_meta
from torrent_tpu.utils.ratelimit import TokenBucket

__all__ = [
    "bencode",
    "bdecode",
    "BencodeError",
    "parse_metainfo",
    "Metainfo",
    "InfoDict",
    "FileEntry",
    "announce",
    "scrape",
    "TrackerError",
    "AnnounceEvent",
    "AnnounceInfo",
    "AnnounceResponse",
    "Client",
    "ClientConfig",
    "Torrent",
    "TorrentConfig",
    "TorrentState",
    "Storage",
    "StorageMethod",
    "FsStorage",
    "MemoryStorage",
    "verify_pieces",
    "TokenBucket",
    "make_torrent",
    "Magnet",
    "parse_magnet",
    "MetainfoV2",
    "InfoDictV2",
    "V2File",
    "parse_metainfo_v2",
    "V2SessionMeta",
    "v2_session_meta",
    "__version__",
]

# v2 (BEP 52) pipeline entry points — import-on-demand like the other
# jax-touching subsystems: torrent_tpu.models.v2.{build_v2, verify_v2,
# hash_file_v2}.

# Heavier subsystems stay import-on-demand (no jax import at package
# import time): torrent_tpu.models.verifier.TPUVerifier,
# torrent_tpu.parallel.bulk.verify_library, torrent_tpu.net.dht.DHTNode,
# torrent_tpu.bridge.service.BridgeServer.
