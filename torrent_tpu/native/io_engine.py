"""Python face of the native batched-read engine (io_engine.cpp).

``NativeIOEngine.read_batch`` reads many pieces of a torrent into one
staging buffer using the C++ pread thread pool; ``Storage.read_batch``
routes through it automatically when the engine is available (see
storage/storage.py), with the pure-Python path as fallback — identical
semantics either way (tests/test_native_io.py runs both differentially).
"""

from __future__ import annotations

import ctypes

import numpy as np

from torrent_tpu.analysis.sanitizer import named_lock

_lib = None
_lib_lock = named_lock("native._lib_lock")
_lib_tried = False


def _get_lib():
    global _lib, _lib_tried
    with _lib_lock:
        if not _lib_tried:
            _lib_tried = True
            from torrent_tpu.native.build import load

            _lib = load()
        return _lib


def native_available() -> bool:
    return _get_lib() is not None


class NativeIOError(OSError):
    pass


class NativeIOEngine:
    """A pread(2) thread pool reading piece batches into staging buffers.

    One engine per process is plenty (the pool is batch-serial by design —
    the verify pipeline has exactly one batch in the disk stage at a time).
    """

    def __init__(self, n_threads: int = 8):
        lib = _get_lib()
        if lib is None:
            raise NativeIOError("native io engine unavailable (no toolchain?)")
        self._lib = lib
        self._handle = lib.tt_io_create(int(n_threads))
        self._lock = named_lock("native.io_engine._lock")  # C pool services one batch at a time

    def close(self) -> None:
        if self._handle:
            self._lib.tt_io_destroy(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass

    def read_segments(
        self,
        paths: list[str],
        segments: list[tuple[int, int, int, int]],
        out: np.ndarray,
    ) -> None:
        """Read ``(file_index, file_offset, out_offset, length)`` segments.

        ``out`` must be a writable C-contiguous uint8 array; raises
        ``NativeIOError`` if any segment cannot be fully read.
        """
        if out.dtype != np.uint8 or not out.flags["C_CONTIGUOUS"] or not out.flags["WRITEABLE"]:
            raise ValueError("out must be a writable C-contiguous uint8 array")
        self.read_into(paths, segments, out.ctypes.data, out.size, keepalive=out)

    def read_into(
        self,
        paths: list[str],
        segments,
        base_addr: int,
        extent: int,
        keepalive=None,
        statuses: np.ndarray | None = None,
    ) -> int:
        """Segment reads into raw memory ``[base_addr, base_addr+extent)``.

        The strided entry point: ``Storage.read_batch`` computes absolute
        byte offsets into a row-strided staging view, so out_offsets here
        are *memory* offsets, not logical array indices. ``keepalive``
        pins the owning buffer for the duration of the call.

        ``statuses``: optional caller-owned ``int32[n_segments]`` array.
        When given, per-segment errnos land there and a failed segment
        does NOT raise — the mark-and-continue contract the zero-copy
        ingest path needs (a torn piece becomes an ``nblocks=0`` sentinel
        row, not an aborted batch). Returns the engine rc (0 = every
        segment read fully); without ``statuses`` a nonzero rc raises
        :class:`NativeIOError` as before.
        """
        seg_arr = np.asarray(segments, dtype=np.int64)
        if seg_arr.size == 0:
            return 0
        if seg_arr.ndim != 2 or seg_arr.shape[1] != 4:
            raise ValueError("segments must be (file_index, file_off, out_off, len) quads")
        ends = seg_arr[:, 2] + seg_arr[:, 3]
        if (seg_arr[:, 3] < 0).any() or (seg_arr[:, 2] < 0).any() or int(ends.max()) > extent:
            raise ValueError("segment exceeds output buffer")
        if (seg_arr[:, 0] < 0).any() or int(seg_arr[:, 0].max()) >= len(paths):
            raise ValueError("segment file index out of range")
        path_arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
        raise_on_error = statuses is None
        if statuses is None:
            statuses = np.zeros(seg_arr.shape[0], dtype=np.int32)
        elif (
            statuses.dtype != np.int32
            or statuses.shape != (seg_arr.shape[0],)
        ):
            raise ValueError("statuses must be int32[n_segments]")
        # pipeline-ledger "read" stage: the batched pread is the storage
        # boundary of the read_batch paths (read_pieces_chunk instruments
        # the per-piece Python path; the two never overlap)
        from torrent_tpu.obs.ledger import pipeline_ledger

        with pipeline_ledger().track("read", int(seg_arr[:, 3].sum())):
            with self._lock:
                rc = self._lib.tt_io_read_batch(
                    self._handle,
                    path_arr,
                    len(paths),
                    seg_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    seg_arr.shape[0],
                    ctypes.cast(base_addr, ctypes.POINTER(ctypes.c_uint8)),
                    statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                )
        del keepalive
        if rc != 0 and raise_on_error:
            bad = np.nonzero(statuses)[0]
            first = int(bad[0]) if bad.size else -1
            raise NativeIOError(
                f"native read failed (rc={rc}) on segment {first}: "
                f"{seg_arr[first].tolist() if first >= 0 else '?'}"
            )
        return int(rc)


_engine = None
_engine_lock = named_lock("native._engine_lock")
_engine_threads: int | None = None
_threads_conflict_warned = False


def get_engine(n_threads: int | None = None):
    """Process-global engine (or None when native IO is unavailable).

    The FIRST caller's ``n_threads`` (or ``TT_IO_THREADS``, default 8)
    sizes the pread pool for the whole process; a later caller asking
    for a different count gets the existing engine — warned once, never
    silently — because resizing a pool with batches in flight isn't
    worth the churn for a tuning knob. Set ``TT_IO_THREADS`` before
    first use to size it deterministically (documented in README).
    """
    global _engine, _engine_threads, _threads_conflict_warned
    with _engine_lock:
        if _engine is None and native_available():
            import os

            threads = n_threads or int(os.environ.get("TT_IO_THREADS", "8"))
            _engine = NativeIOEngine(threads)
            _engine_threads = threads
        elif (
            _engine is not None
            and n_threads is not None
            and n_threads != _engine_threads
            and not _threads_conflict_warned
        ):
            _threads_conflict_warned = True
            from torrent_tpu.utils.log import get_logger

            get_logger("native").warning(
                "get_engine(n_threads=%d) ignored: the process-global pread "
                "pool was already built with %s threads (first caller wins; "
                "set TT_IO_THREADS before first use)",
                n_threads, _engine_threads,
            )
        return _engine
