"""Build the native IO engine shared library with g++.

No pybind11/setuptools machinery needed for a C-ABI .so; one compiler
invocation, cached next to the source and rebuilt when the source is
newer. Import-time use goes through ``load()`` which returns None (pure-
Python fallback) whenever a toolchain or binary is unavailable — the
framework never hard-requires the native engine.
"""

from __future__ import annotations

import os
import pathlib
import subprocess

_SRC = pathlib.Path(__file__).with_name("io_engine.cpp")
_LIB = pathlib.Path(__file__).with_name("libtorrent_tpu_io.so")


def build(force: bool = False) -> pathlib.Path | None:
    """Compile the engine if needed; returns the .so path or None."""
    if not _SRC.exists():
        return None
    if not force and _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
        return _LIB
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        str(_SRC),
        "-o",
        str(_LIB),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return _LIB


def load():
    """ctypes handle to the built engine, or None if unavailable."""
    import ctypes

    path = build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        # Stale/foreign binary (other arch, older glibc): rebuild from
        # source once before giving up on the native engine.
        path = build(force=True)
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            return None
    lib.tt_io_create.restype = ctypes.c_void_p
    lib.tt_io_create.argtypes = [ctypes.c_int]
    lib.tt_io_destroy.restype = None
    lib.tt_io_destroy.argtypes = [ctypes.c_void_p]
    lib.tt_io_read_batch.restype = ctypes.c_int
    lib.tt_io_read_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.tt_rc4_init.restype = None
    lib.tt_rc4_init.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_int32,
    ]
    lib.tt_rc4_crypt.restype = None
    # buf is mutated in place (keystream xor), hence void* not char*
    lib.tt_rc4_crypt.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int64,
    ]
    return lib


if __name__ == "__main__":
    out = build(force=True)
    print(f"built: {out}" if out else "build failed")
