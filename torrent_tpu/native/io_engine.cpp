// Native batched-read engine for the hash plane's disk stage.
//
// The reference's storage path is one async seek/read per block
// (storage.ts:150-172 fsStorage.get); the Python port of that is fine
// for the swarm's 16 KiB blocks but cannot feed a TPU verifier at GiB/s:
// per-call overhead (Python frames, GIL, one syscall per segment through
// a shared file cursor) dominates. This engine is the C++ data-loader
// the batch path calls instead:
//
// - the caller flattens a piece batch into (file, file_offset, out_offset,
//   length) segments — multi-file boundary spanning already resolved;
// - a persistent thread pool services segments with positional pread(2)
//   (no shared cursor, no locking between readers) straight into the
//   caller's staging buffer (the same buffer jax.device_put uploads from);
// - file descriptors are opened once per batch and shared read-only
//   across threads (pread is thread-safe by contract).
//
// Exposed as a tiny C ABI for ctypes — no pybind11 in this image.
// Build: torrent_tpu/native/build.py (g++ -O2 -shared -fPIC -pthread).

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Segment {
  int32_t file_index;   // index into the batch's path table
  int64_t file_offset;  // byte offset within that file
  int64_t out_offset;   // byte offset within the output buffer
  int64_t length;       // bytes to read
};

// Read one segment fully; returns 0 on success, else errno-style code.
// Short reads past EOF are reported as EIO-like failure (-1): a piece
// that cannot be fully read must not verify.
int read_segment(int fd, const Segment& seg, uint8_t* out) {
  int64_t done = 0;
  while (done < seg.length) {
    ssize_t n = pread(fd, out + seg.out_offset + done,
                      static_cast<size_t>(seg.length - done),
                      static_cast<off_t>(seg.file_offset + done));
    if (n < 0) {
      if (errno == EINTR) continue;
      return errno ? errno : -1;
    }
    if (n == 0) return -1;  // EOF before the segment was satisfied
    done += n;
  }
  return 0;
}

// All per-batch state lives in one heap object handed to workers via
// shared_ptr, so a straggler thread that wakes late can only ever touch
// ITS batch's counters — never a newer batch's (claiming an index from a
// fresh batch's counter while holding stale segment pointers would
// double-claim segments and return before the buffer is complete).
// `done` is flipped and cv_done notified under the mutex; checking the
// predicate under the same mutex in submit() makes the wakeup lossless.
struct Batch {
  const Segment* segs;
  const int* fds;
  uint8_t* out;
  int32_t* statuses;
  int64_t n_segs;
  std::atomic<int64_t> next{0};
  std::atomic<int64_t> remaining;
};

struct Pool {
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  std::shared_ptr<Batch> current;  // guarded by mu
  uint64_t generation = 0;         // guarded by mu
  bool batch_done = false;         // guarded by mu
  bool shutting_down = false;

  explicit Pool(int n_threads) {
    for (int i = 0; i < n_threads; ++i) {
      workers.emplace_back([this] { run(); });
    }
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lock(mu);
      shutting_down = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
  }

  void run() {
    uint64_t seen = 0;
    for (;;) {
      std::shared_ptr<Batch> batch;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_work.wait(lock, [&] { return shutting_down || generation != seen; });
        if (shutting_down) return;
        seen = generation;
        batch = current;
      }
      for (;;) {
        int64_t i = batch->next.fetch_add(1);
        if (i >= batch->n_segs) break;
        const Segment& s = batch->segs[i];
        batch->statuses[i] = read_segment(batch->fds[s.file_index], s, batch->out);
        if (batch->remaining.fetch_sub(1) == 1) {
          std::lock_guard<std::mutex> lock(mu);
          batch_done = true;
          cv_done.notify_all();
        }
      }
    }
  }

  // Returns 0 if every segment read cleanly; else the first error code.
  int submit(const Segment* s, int64_t n, const int* f, uint8_t* o,
             int32_t* st) {
    if (n == 0) return 0;
    auto batch = std::make_shared<Batch>();
    batch->segs = s;
    batch->fds = f;
    batch->out = o;
    batch->statuses = st;
    batch->n_segs = n;
    batch->remaining.store(n);
    {
      std::lock_guard<std::mutex> lock(mu);
      current = batch;
      batch_done = false;
      ++generation;
    }
    cv_work.notify_all();
    {
      std::unique_lock<std::mutex> lock(mu);
      cv_done.wait(lock, [&] { return batch_done; });
    }
    for (int64_t i = 0; i < n; ++i)
      if (st[i] != 0) return st[i];
    return 0;
  }
};

}  // namespace

extern "C" {

// Opaque engine handle.
void* tt_io_create(int n_threads) {
  if (n_threads < 1) n_threads = 1;
  if (n_threads > 64) n_threads = 64;
  return new Pool(n_threads);
}

void tt_io_destroy(void* engine) { delete static_cast<Pool*>(engine); }

// Read a batch of segments from a set of files into `out`.
//
// paths:      NUL-terminated UTF-8 file paths, n_files of them
// segs:       packed int64 quads [file_index, file_offset, out_offset, length]
//             (file_index stored as int64 for a uniform array layout)
// statuses:   caller-allocated int32[n_segs] scratch (per-segment errno)
//
// Returns 0 on full success; first nonzero errno otherwise (including
// -1 for EOF-short reads and open() failures reported per segment).
int tt_io_read_batch(void* engine, const char** paths, int32_t n_files,
                     const int64_t* segs, int64_t n_segs, uint8_t* out,
                     int32_t* statuses) {
  Pool* pool = static_cast<Pool*>(engine);
  std::vector<int> fds(n_files, -1);
  for (int32_t i = 0; i < n_files; ++i) {
    fds[i] = open(paths[i], O_RDONLY | O_CLOEXEC);
  }
  std::vector<Segment> packed(static_cast<size_t>(n_segs));
  int rc = 0;
  for (int64_t i = 0; i < n_segs; ++i) {
    const int64_t* q = segs + i * 4;
    packed[i].file_index = static_cast<int32_t>(q[0]);
    packed[i].file_offset = q[1];
    packed[i].out_offset = q[2];
    packed[i].length = q[3];
    if (q[0] < 0 || q[0] >= n_files || fds[q[0]] < 0) {
      // missing file: fail fast before touching the pool
      statuses[i] = ENOENT;
      rc = ENOENT;
    } else {
      statuses[i] = 0;
    }
  }
  if (rc == 0) {
    rc = pool->submit(packed.data(), n_segs, fds.data(), out, statuses);
  }
  for (int fd : fds)
    if (fd >= 0) close(fd);
  return rc;
}

// ------------------------------------------------------------------ RC4
//
// Stream cipher for MSE/PE peer-connection obfuscation (net/mse.py).
// RC4 is inherently sequential (one byte of state update per keystream
// byte) so it cannot ride the TPU hash plane; a C loop runs ~100x the
// pure-Python fallback and keeps encrypted peer connections off the
// session's critical path. State is a caller-owned 258-byte buffer
// (256-byte permutation + i + j) so the library stays allocation-free.

void tt_rc4_init(uint8_t* state, const uint8_t* key, int32_t keylen) {
  if (keylen <= 0) return;  // caller validates; never SIGFPE on i % 0
  uint8_t* s = state;
  for (int i = 0; i < 256; ++i) s[i] = static_cast<uint8_t>(i);
  uint8_t j = 0;
  for (int i = 0; i < 256; ++i) {
    j = static_cast<uint8_t>(j + s[i] + key[i % keylen]);
    uint8_t t = s[i];
    s[i] = s[j];
    s[j] = t;
  }
  state[256] = 0;  // i
  state[257] = 0;  // j
}

void tt_rc4_crypt(uint8_t* state, uint8_t* buf, int64_t n) {
  uint8_t* s = state;
  uint8_t i = state[256], j = state[257];
  for (int64_t k = 0; k < n; ++k) {
    i = static_cast<uint8_t>(i + 1);
    j = static_cast<uint8_t>(j + s[i]);
    uint8_t t = s[i];
    s[i] = s[j];
    s[j] = t;
    buf[k] ^= s[static_cast<uint8_t>(s[i] + s[j])];
  }
  state[256] = i;
  state[257] = j;
}

}  // extern "C"
