"""BEP 35 torrent signing over the raw info-dict span.

Structure follows BEP 35: a root-level ``signatures`` dict keyed by the
signer's identity string; each entry holds an optional ``certificate``,
an optional extension-``info`` dict, and the ``signature``. The signed
message is the EXACT wire bytes of the ``info`` value (the infohash
preimage, taken from the original buffer the way the raw-span infohash
is — never a re-encode) concatenated with the bencoded extension-info
dict when one is present, per the BEP.

The supported scheme is Ed25519 — the keys BEP 46 mutable torrents and
BEP 44 DHT items already use — with the signer's 32-byte public key
carried in ``certificate``. BEP 35 leaves certificate contents to the
recognized scheme; x509/RSA chains are REFUSED (``verify_torrent``
returns False), never mis-verified.

Because ``signatures`` lives at the root, signing leaves the infohash
untouched: a signed and an unsigned copy are the same swarm. The
reference has no counterpart (rclarey/torrent implements no BEP 35).
"""

from __future__ import annotations

from torrent_tpu.codec.bencode import (
    BencodeError,
    _decode_at,
    bdecode_with_info_span,
    bencode,
)
from torrent_tpu.utils import ed25519

ED25519_PUB_LEN = 32
SIG_LEN = 64


def _enc_str(b: bytes) -> bytes:
    return str(len(b)).encode("ascii") + b":" + b


def _dict_entry_spans(buf: bytes, i: int) -> dict[bytes, tuple[int, int]]:
    """``key -> (value_start, value_end)`` for the bencoded dict whose
    ``d`` sits at ``buf[i]``. Wire-byte spans, no value decoding beyond
    what skipping requires; raises BencodeError on malformation."""
    if i >= len(buf) or buf[i] != 0x64:  # 'd'
        raise BencodeError("not a dict")
    i += 1
    out: dict[bytes, tuple[int, int]] = {}
    while True:
        if i >= len(buf):
            raise BencodeError("unterminated dict")
        if buf[i] == 0x65:  # 'e'
            return out
        key, i = _decode_at(buf, i)
        if not isinstance(key, bytes):
            raise BencodeError("dict key is not a bytestring")
        start = i
        _, i = _decode_at(buf, i)
        out[key] = (start, i)


def _top_value_span(buf: bytes, key: bytes) -> tuple[int, int] | None:
    try:
        return _dict_entry_spans(buf, 0).get(key)
    except BencodeError:
        return None


def sign_torrent(
    data: bytes,
    seed: bytes,
    signer: str,
    info_ext: dict | None = None,
) -> bytes:
    """Return new .torrent bytes with a ``signatures[signer]`` entry.

    ``seed`` is the 32-byte Ed25519 seed (same format the BEP 46 tools
    use); ``info_ext`` optionally carries BEP 35 extension fields, which
    are covered by the signature. Re-signing with the same identity
    replaces that identity's entry; other signers' entries survive
    BYTE-FOR-BYTE (their signatures cover their own wire ext bytes).

    The output is assembled by splicing: the ``info`` value and foreign
    signature entries are copied verbatim from the input buffer — never
    re-encoded — so a non-canonical wild torrent keeps its infohash and
    its existing signatures; only the top-level frame and our own entry
    are freshly (canonically) encoded.
    """
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    decoded, span = bdecode_with_info_span(data)
    if span is None:
        raise ValueError("not a .torrent: no info dict")
    raw_info = data[span[0] : span[1]]
    msg = raw_info

    entry: dict = {b"certificate": ed25519.publickey(seed)}
    if info_ext:
        # our entry is emitted via the same canonical encoder, so these
        # exact bytes appear on the wire — signed == emitted
        entry[b"info"] = info_ext
        msg += bencode(info_ext)
    entry[b"signature"] = ed25519.sign(seed, msg)

    # existing signers' entries: raw wire spans, preserved verbatim
    raw_entries: dict[bytes, bytes] = {}
    sig_span = _top_value_span(data, b"signatures")
    if sig_span is not None:
        try:
            for k, (s, e) in _dict_entry_spans(data, sig_span[0]).items():
                raw_entries[k] = data[s:e]
        except BencodeError:
            raw_entries = {}  # malformed signatures value: start fresh
    raw_entries[signer.encode("utf-8")] = bencode(entry)
    sig_wire = (
        b"d"
        + b"".join(_enc_str(k) + raw_entries[k] for k in sorted(raw_entries))
        + b"e"
    )

    out = bytearray(b"d")
    for k in sorted(set(decoded) | {b"signatures"}):
        out += _enc_str(k)
        if k == b"info":
            out += raw_info
        elif k == b"signatures":
            out += sig_wire
        else:
            out += bencode(decoded[k])
    out += b"e"
    return bytes(out)


def list_signers(data: bytes) -> list[str]:
    """Identity strings with a structurally-plausible signature entry."""
    try:
        decoded, _ = bdecode_with_info_span(data)
    except BencodeError:
        return []
    sigs = decoded.get(b"signatures")
    if not isinstance(sigs, dict):
        return []
    out = []
    for name, entry in sigs.items():
        if isinstance(entry, dict) and isinstance(entry.get(b"signature"), bytes):
            try:
                out.append(name.decode("utf-8"))
            except UnicodeDecodeError:
                continue
    return out


def ensure_signed(data: bytes, signer: str, pub: bytes) -> None:
    """THE gate: raise ValueError unless ``signer``'s signature verifies
    under the 32-byte trusted key. Every require-signed surface (library
    ``add_torrent_bytes``, CLI download/update, feed auto-add) funnels
    through here so the check — and its failure message — cannot drift.

    ``pub`` is mandatory and validated: a missing/short key must never
    silently degrade the gate to trusting the attacker-supplied embedded
    certificate."""
    if not isinstance(pub, bytes) or len(pub) != ED25519_PUB_LEN:
        raise ValueError("trusted key must be 32 bytes (Ed25519 public key)")
    if not verify_torrent(data, signer, pub):
        raise ValueError(
            f"no valid BEP 35 signature by {signer!r} under the trusted key"
        )


def has_embedded_certificate(data: bytes, signer: str) -> bool:
    """True when ``signer``'s entry carries a ``certificate`` field.

    The CLI uses this to distinguish "unverifiable without a trusted key
    (BEP 35 allows out-of-band keys)" from "the embedded key does not
    verify" — one classification, shared by every command."""
    try:
        decoded, _ = bdecode_with_info_span(data)
    except BencodeError:
        return False
    sigs = decoded.get(b"signatures")
    if not isinstance(sigs, dict):
        return False
    entry = sigs.get(signer.encode("utf-8"))
    return isinstance(entry, dict) and b"certificate" in entry


def verify_torrent(data: bytes, signer: str, pub: bytes | None = None) -> bool:
    """True iff ``signer``'s signature verifies over this torrent.

    ``pub`` is the trusted 32-byte public key. When given, an embedded
    certificate must MATCH it (an attacker replacing cert+signature
    together must not pass); when omitted, the embedded certificate is
    used — caller trusts whoever it names, which is only meaningful if
    the torrent arrived over a trusted channel. Anything structurally
    non-Ed25519 (x509 chains, wrong lengths) fails closed.
    """
    try:
        decoded, span = bdecode_with_info_span(data)
    except BencodeError:
        return False
    if span is None:
        return False
    sigs = decoded.get(b"signatures")
    if not isinstance(sigs, dict):
        return False
    entry = sigs.get(signer.encode("utf-8"))
    if not isinstance(entry, dict):
        return False
    sig = entry.get(b"signature")
    if not isinstance(sig, bytes) or len(sig) != SIG_LEN:
        return False
    cert = entry.get(b"certificate")
    if cert is not None and (
        not isinstance(cert, bytes) or len(cert) != ED25519_PUB_LEN
    ):
        return False  # not a raw Ed25519 key: refuse, don't guess
    if pub is not None:
        if len(pub) != ED25519_PUB_LEN:
            return False
        if cert is not None and cert != pub:
            return False
        key = pub
    else:
        if cert is None:
            return False
        key = cert
    msg = data[span[0] : span[1]]
    if entry.get(b"info") is not None:
        if not isinstance(entry[b"info"], dict):
            return False
        # spec-faithful: the signature covers the entry's ext dict WIRE
        # bytes — a foreign signer's non-canonical encoding must verify
        # as written, not as our encoder would have written it
        try:
            sig_span = _top_value_span(data, b"signatures")
            assert sig_span is not None
            entry_span = _dict_entry_spans(data, sig_span[0])[
                signer.encode("utf-8")
            ]
            ext_span = _dict_entry_spans(data, entry_span[0])[b"info"]
        except (BencodeError, KeyError, AssertionError):
            return False
        msg += data[ext_span[0] : ext_span[1]]
    return ed25519.verify(key, msg, sig)
