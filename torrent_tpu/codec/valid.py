"""Runtime validator combinators (reference layer L1: valid.ts, 47 LoC).

Tiny predicates composed into shape checks for untrusted bdecoded data —
the reference's ``obj/arr/inst/or/num/undef`` combinators (valid.ts:7-47)
re-thought for Python: each validator is a callable ``(value) -> bool``.
Used by metainfo parsing and tracker response parsing before any cast.
"""

from __future__ import annotations

from typing import Any, Callable

Validator = Callable[[Any], bool]


def is_int(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def is_bytes(v: Any) -> bool:
    return isinstance(v, bytes)


def is_dict(v: Any) -> bool:
    return isinstance(v, dict)


def num() -> Validator:
    """Matches an integer (valid.ts:45)."""
    return is_int


def bstr() -> Validator:
    """Matches a bytestring (the decode-side analogue of valid.ts `inst`)."""
    return is_bytes


def absent() -> Validator:
    """Matches a missing optional field (valid.ts:47 `undef`)."""
    return lambda v: v is None


def either(*validators: Validator) -> Validator:
    """Matches if any sub-validator matches (valid.ts:41 `or`)."""

    def check(v: Any) -> bool:
        return any(val(v) for val in validators)

    return check


def optional(validator: Validator) -> Validator:
    return either(absent(), validator)


def arr(item: Validator) -> Validator:
    """Matches a list whose every element matches ``item`` (valid.ts:24)."""

    def check(v: Any) -> bool:
        return isinstance(v, list) and all(item(x) for x in v)

    return check


def obj(shape: dict[bytes, Validator], allow_extra: bool = True) -> Validator:
    """Matches a bytes-keyed dict against a field shape (valid.ts:7).

    Optional fields are expressed with :func:`optional`; extra keys are
    allowed by default (torrents carry arbitrary extra fields — the
    reference's ``extra.torrent`` fixture exercises exactly this).
    """

    def check(v: Any) -> bool:
        if not isinstance(v, dict):
            return False
        for key, validator in shape.items():
            if not validator(v.get(key)):
                return False
        if not allow_extra:
            for key in v:
                if key not in shape:
                    return False
        return True

    return check


def fixed_len_bytes(n: int) -> Validator:
    def check(v: Any) -> bool:
        return isinstance(v, bytes) and len(v) == n

    return check


def multiple_len_bytes(n: int) -> Validator:
    """Bytestring whose length is a positive multiple of ``n`` (pieces blob)."""

    def check(v: Any) -> bool:
        return isinstance(v, bytes) and len(v) > 0 and len(v) % n == 0

    return check
