"""Bencode codec (reference layer L1: bencode.ts, 202 LoC).

Design differences from the reference, deliberate (SURVEY §8.10-11, §8.16):

- **Bytes keys everywhere.** Decoded dicts are keyed by ``bytes``, which is
  what the wire actually carries. This removes the reference's whole
  ``bdecodeBytestringMap`` special case (bencode.ts:168-202) for scrape
  responses keyed by raw 20-byte info hashes — binary keys just work.
- **Canonical sorted-key encode by default** as BEP 3 requires; the
  reference emits insertion order (bencode.ts:56-64) and only round-trips
  correctly because its decoder preserves order. ``sort_keys=False`` gives
  the compat behavior for re-hashing foreign dicts verbatim (Python dicts
  preserve insertion order, so decode→encode is byte-exact either way for
  well-formed canonical input).
- **Real byte buffers**: the encoder writes into one ``bytearray`` instead
  of the reference's push-spread ``number[]`` with 10k chunking
  (bencode.ts:35-42).
- **Strict bounds checks**: truncated ints/strings raise ``BencodeError``
  instead of scanning past the buffer (bencode.ts:77-106).
"""

from __future__ import annotations

from typing import Union

Bencodeable = Union[bytes, bytearray, memoryview, str, int, list, dict]


class BencodeError(ValueError):
    """Malformed bencode input or unencodable value."""


# ---------------------------------------------------------------- encode


def bencode(value: Bencodeable, sort_keys: bool = True) -> bytes:
    """Encode a value to canonical bencode bytes.

    ``str`` is encoded as UTF-8; dict keys may be ``bytes`` or ``str`` and
    are sorted as raw bytes when ``sort_keys`` (BEP 3 canonical form).
    Booleans are rejected (ambiguous — the wire has no bool type).
    """
    out = bytearray()
    _encode_into(value, out, sort_keys)
    return bytes(out)


def _encode_into(value: Bencodeable, out: bytearray, sort_keys: bool) -> None:
    if isinstance(value, bool):
        raise BencodeError("cannot bencode bool")
    if isinstance(value, (bytes, bytearray, memoryview)):
        raw = bytes(value)
        out += str(len(raw)).encode("ascii")
        out += b":"
        out += raw
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out += str(len(raw)).encode("ascii")
        out += b":"
        out += raw
    elif isinstance(value, int):
        out += b"i"
        out += str(value).encode("ascii")
        out += b"e"
    elif isinstance(value, (list, tuple)):
        out += b"l"
        for item in value:
            _encode_into(item, out, sort_keys)
        out += b"e"
    elif isinstance(value, dict):
        out += b"d"
        items = []
        for k, v in value.items():
            if isinstance(k, str):
                kb = k.encode("utf-8")
            elif isinstance(k, (bytes, bytearray, memoryview)):
                kb = bytes(k)
            else:
                raise BencodeError(f"dict key must be bytes/str, got {type(k).__name__}")
            items.append((kb, v))
        if sort_keys:
            items.sort(key=lambda kv: kv[0])
        for kb, v in items:
            _encode_into(kb, out, sort_keys)
            _encode_into(v, out, sort_keys)
        out += b"e"
    else:
        raise BencodeError(f"cannot bencode {type(value).__name__}")


# ---------------------------------------------------------------- decode


def bdecode(data: bytes | bytearray | memoryview, strict: bool = True):
    """Decode bencode bytes into bytes/int/list/dict-with-bytes-keys.

    With ``strict`` (default), trailing bytes after the top-level value are
    an error — the reference silently ignores them.
    """
    buf = bytes(data)
    value, end = _decode_at(buf, 0)
    if strict and end != len(buf):
        raise BencodeError(f"trailing data after bencode value at {end}")
    return value


def bdecode_prefix(data: bytes | bytearray | memoryview):
    """Decode one value from the head of ``data``; return ``(value, end)``.

    ``end`` is the number of bytes consumed. Needed by BEP 9 ut_metadata
    framing, where a bencoded dict is immediately followed by raw piece
    bytes that are not part of the dict.
    """
    buf = bytes(data)
    return _decode_at(buf, 0)


def bdecode_with_info_span(data: bytes | bytearray | memoryview):
    """Decode a top-level dict, also returning the byte span of ``info``.

    Returns ``(value, (start, end) | None)``. The span covers the raw
    bencoded ``info`` dict value, so ``sha1(data[start:end])`` is the
    BEP 3 infohash computed over the *original* bytes — immune to
    key-order or formatting quirks that re-encoding (the reference's
    approach, metainfo.ts:141-143) would have to reproduce exactly.
    """
    buf = bytes(data)
    if not buf or buf[0:1] != b"d":
        raise BencodeError("top-level value is not a dict")
    i = 1
    result: dict = {}
    info_span: tuple[int, int] | None = None
    while True:
        if i >= len(buf):
            raise BencodeError("unterminated dict")
        if buf[i] == 0x65:  # 'e'
            i += 1
            break
        key, i = _decode_at(buf, i)
        if not isinstance(key, bytes):
            raise BencodeError("dict key is not a bytestring")
        start = i
        val, i = _decode_at(buf, i)
        if key == b"info":
            info_span = (start, i)
        result[key] = val
    if len(buf) != i:
        raise BencodeError(f"trailing data after bencode value at {i}")
    return result, info_span


def _decode_at(buf: bytes, i: int):
    if i >= len(buf):
        raise BencodeError(f"unexpected end of input at {i}")
    c = buf[i]
    if c == 0x69:  # 'i'
        end = buf.find(b"e", i + 1)
        if end < 0:
            raise BencodeError("unterminated integer")
        body = buf[i + 1 : end]
        _check_int_body(body)
        return int(body), end + 1
    if 0x30 <= c <= 0x39:  # digit: bytestring
        colon = buf.find(b":", i)
        if colon < 0:
            raise BencodeError("unterminated string length")
        lenbody = buf[i:colon]
        if not lenbody.isdigit():
            raise BencodeError(f"bad string length {lenbody!r}")
        if len(lenbody) > 1 and lenbody[0] == 0x30:
            raise BencodeError("string length has leading zero")
        n = int(lenbody)
        start = colon + 1
        if start + n > len(buf):
            raise BencodeError("truncated string")
        return buf[start : start + n], start + n
    if c == 0x6C:  # 'l'
        i += 1
        items = []
        while True:
            if i >= len(buf):
                raise BencodeError("unterminated list")
            if buf[i] == 0x65:
                return items, i + 1
            item, i = _decode_at(buf, i)
            items.append(item)
    if c == 0x64:  # 'd'
        i += 1
        d: dict = {}
        while True:
            if i >= len(buf):
                raise BencodeError("unterminated dict")
            if buf[i] == 0x65:
                return d, i + 1
            key, i = _decode_at(buf, i)
            if not isinstance(key, bytes):
                raise BencodeError("dict key is not a bytestring")
            val, i = _decode_at(buf, i)
            d[key] = val
    raise BencodeError(f"unexpected byte {c:#x} at {i}")


def _check_int_body(body: bytes) -> None:
    if not body:
        raise BencodeError("empty integer")
    digits = body[1:] if body[0:1] == b"-" else body
    if not digits.isdigit():
        raise BencodeError(f"bad integer {body!r}")
    if len(digits) > 1 and digits[0] == 0x30:
        raise BencodeError(f"integer has leading zero: {body!r}")
    if body == b"-0":
        raise BencodeError("negative zero")
