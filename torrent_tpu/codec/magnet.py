"""Magnet URI parsing (BEP 9 §magnet — a reference roadmap item).

The reference lists "Magnet Links" unchecked (README.md:39); this module
plus ``net/extension.py`` (BEP 10 extension protocol + ut_metadata) and
``session/metadata.py`` (the fetch driver) complete it: a client can join
a swarm from ``magnet:?xt=urn:btih:...`` alone and learn the info dict
from its peers.

Supported fields: ``xt`` (btih, 40-hex or 32-base32), ``dn`` display
name, ``tr`` tracker URLs (repeatable), ``x.pe`` direct peer addresses
(repeatable, BEP 9 extension used by several clients for trackerless
bootstrap).
"""

from __future__ import annotations

import base64
import binascii
from dataclasses import dataclass, field
from urllib.parse import parse_qs, quote, urlparse


class MagnetError(ValueError):
    pass


# BEP 53 cap: magnet URIs are untrusted; a so= range may not select more
# files than any real torrent plausibly has (prevents range bombs)
MAX_SELECT_ONLY = 100_000


@dataclass(frozen=True)
class Magnet:
    # v1 (btih, 20 bytes) and/or v2 (btmh sha2-256 multihash, 32 bytes)
    # exact topics; hybrid magnets carry both, pure-v2 only the latter
    info_hash: bytes | None = None
    display_name: str | None = None
    trackers: tuple[str, ...] = ()
    peer_addrs: tuple[tuple[str, int], ...] = field(default_factory=tuple)
    info_hash_v2: bytes | None = None
    # BEP 53 "select only": file indices to download (None = everything)
    select_only: tuple[int, ...] | None = None
    # BEP 9 §"magnet URI format" / BEP 19: ws= webseed URLs
    web_seeds: tuple[str, ...] = ()
    # BEP 46 mutable pointer: xs=urn:btpk:<ed25519 pubkey hex> (+ s=<salt
    # hex>) — the infohash is resolved through a BEP 44 mutable item
    mutable_key: bytes | None = None
    mutable_salt: bytes = b""

    @property
    def wire_hash(self) -> bytes:
        """The 20-byte infohash used on the wire (registry key, handshake,
        tracker/DHT announces): btih as-is, or the TRUNCATED sha-256 for
        a pure-v2 (btmh-only) magnet per BEP 52."""
        if self.info_hash is not None:
            return self.info_hash
        if self.info_hash_v2 is None:
            raise MagnetError(
                "mutable (btpk) magnet has no wire hash until resolved via BEP 44"
            )
        return self.info_hash_v2[:20]

    def to_uri(self) -> str:
        topics = []
        if self.info_hash is not None:
            topics.append(f"xt=urn:btih:{self.info_hash.hex()}")
        if self.info_hash_v2 is not None:
            topics.append(f"xt=urn:btmh:1220{self.info_hash_v2.hex()}")
        if self.mutable_key is not None:
            topics.append(f"xs=urn:btpk:{self.mutable_key.hex()}")
            if self.mutable_salt:
                topics.append(f"s={self.mutable_salt.hex()}")
        if not topics:
            raise MagnetError("magnet needs at least one exact topic")
        parts = ["magnet:?" + topics[0]] + topics[1:]
        if self.display_name:
            parts.append(f"dn={quote(self.display_name)}")
        for tr in self.trackers:
            parts.append(f"tr={quote(tr, safe='')}")
        for host, port in self.peer_addrs:
            h = f"[{host}]" if ":" in host else host  # IPv6 re-bracketing
            parts.append(f"x.pe={h}:{port}")
        for ws in self.web_seeds:
            parts.append(f"ws={quote(ws, safe='')}")
        if self.select_only is not None:
            # BEP 53: compress consecutive runs ("0,2,4-7")
            runs: list[str] = []
            idxs = sorted(set(self.select_only))
            i = 0
            while i < len(idxs):
                j = i
                while j + 1 < len(idxs) and idxs[j + 1] == idxs[j] + 1:
                    j += 1
                runs.append(
                    str(idxs[i]) if i == j else f"{idxs[i]}-{idxs[j]}"
                )
                i = j + 1
            parts.append("so=" + ",".join(runs))
        return "&".join(parts)


def mutable_magnet_uri(pubkey: bytes, salt: bytes = b"") -> str:
    """BEP 46 shareable URI for a publisher's key (+ optional salt)."""
    if len(pubkey) != 32:
        raise MagnetError("btpk public key must be 32 bytes")
    return Magnet(mutable_key=pubkey, mutable_salt=salt).to_uri()


def _decode_btih(value: str) -> bytes:
    if len(value) == 40:
        try:
            return binascii.unhexlify(value)
        except binascii.Error as e:
            raise MagnetError(f"bad hex info hash {value!r}") from e
    if len(value) == 32:
        try:
            return base64.b32decode(value.upper())
        except binascii.Error as e:
            raise MagnetError(f"bad base32 info hash {value!r}") from e
    raise MagnetError(f"info hash must be 40 hex or 32 base32 chars, got {value!r}")


def parse_magnet(uri: str) -> Magnet:
    """Parse a magnet URI; raises ``MagnetError`` on anything malformed."""
    parsed = urlparse(uri)
    if parsed.scheme != "magnet":
        raise MagnetError(f"not a magnet URI: {uri!r}")
    params = parse_qs(parsed.query)
    # bare "so=" is meaningful (explicit empty selection) but parse_qs
    # drops blank values by default — look it up with blanks kept
    params_blank = parse_qs(parsed.query, keep_blank_values=True)
    info_hash = None
    info_hash_v2 = None
    for xt in params.get("xt", []):
        if xt.startswith("urn:btih:") and info_hash is None:
            info_hash = _decode_btih(xt[len("urn:btih:") :])
        elif xt.startswith("urn:btmh:") and info_hash_v2 is None:
            # BEP 52: sha2-256 multihash — 0x12 (sha2-256) 0x20 (32 bytes).
            # Unrecognized algos/shapes are SKIPPED, not fatal: a hybrid
            # magnet's btih topic must stay usable whatever rides beside it
            mh = xt[len("urn:btmh:") :]
            if len(mh) == 68 and mh.lower().startswith("1220"):
                try:
                    info_hash_v2 = binascii.unhexlify(mh[4:])
                except binascii.Error:
                    pass
    # BEP 46: xs=urn:btpk:<64 hex> names an ed25519 key whose BEP 44
    # mutable item carries the current infohash; s=<hex> is its salt.
    # Malformed btpk/s values SKIP the mutable pointer, same policy as
    # unrecognized btmh shapes above — a magnet with a usable btih/btmh
    # beside a bad pointer must still join; only a magnet whose SOLE
    # topic was the (unusable) pointer fails, via the no-topic error.
    mutable_key = None
    mutable_salt = b""
    for xs in params.get("xs", []):
        if xs.startswith("urn:btpk:") and mutable_key is None:
            pk_hex = xs[len("urn:btpk:") :]
            if len(pk_hex) == 64:
                try:
                    mutable_key = binascii.unhexlify(pk_hex)
                except binascii.Error:
                    pass
    if mutable_key is not None and params.get("s"):
        try:
            mutable_salt = binascii.unhexlify(params["s"][0])
        except binascii.Error:
            mutable_key = None  # pointer unusable without its salt
            mutable_salt = b""
    if info_hash is None and info_hash_v2 is None and mutable_key is None:
        raise MagnetError("magnet URI has no urn:btih/btmh/btpk topic")
    peers: list[tuple[str, int]] = []
    for pe in params.get("x.pe", []):
        host, _, port_s = pe.rpartition(":")
        try:
            port = int(port_s)
        except ValueError as e:
            raise MagnetError(f"bad x.pe address {pe!r}") from e
        if not host or not 0 < port < 65536:
            raise MagnetError(f"bad x.pe address {pe!r}")
        peers.append((host.strip("[]"), port))
    select_only: tuple[int, ...] | None = None
    if "so" in params_blank:
        # BEP 53: "so=0,2,4-7" — indices and inclusive ranges; a bare
        # "so=" is an explicit EMPTY selection (download nothing yet).
        # A magnet carrying an unparsable so= fails loudly (silently
        # downloading EVERYTHING would violate the user's selection),
        # and the total selection is capped: magnet URIs are untrusted
        # input and "so=0-9999999999" must not materialize a range bomb.
        picked: set[int] = set()
        for part in params_blank["so"][0].split(","):
            part = part.strip()
            if not part:
                continue
            lo, dash, hi = part.partition("-")
            try:
                a = int(lo)
                b = int(hi) if dash else a
                if a < 0 or b < a:
                    raise ValueError
            except ValueError as e:
                raise MagnetError(f"bad so= selection {part!r}") from e
            if b - a + 1 > MAX_SELECT_ONLY - len(picked):
                raise MagnetError(f"so= selection exceeds {MAX_SELECT_ONLY} files")
            picked.update(range(a, b + 1))
        select_only = tuple(sorted(picked))
    return Magnet(
        info_hash=info_hash,
        info_hash_v2=info_hash_v2,
        display_name=params["dn"][0] if params.get("dn") else None,
        trackers=tuple(params.get("tr", [])),
        peer_addrs=tuple(peers),
        select_only=select_only,
        web_seeds=tuple(u for u in params.get("ws", []) if u),
        mutable_key=mutable_key,
        mutable_salt=mutable_salt,
    )
