"""Magnet URI parsing (BEP 9 §magnet — a reference roadmap item).

The reference lists "Magnet Links" unchecked (README.md:39); this module
plus ``net/extension.py`` (BEP 10 extension protocol + ut_metadata) and
``session/metadata.py`` (the fetch driver) complete it: a client can join
a swarm from ``magnet:?xt=urn:btih:...`` alone and learn the info dict
from its peers.

Supported fields: ``xt`` (btih, 40-hex or 32-base32), ``dn`` display
name, ``tr`` tracker URLs (repeatable), ``x.pe`` direct peer addresses
(repeatable, BEP 9 extension used by several clients for trackerless
bootstrap).
"""

from __future__ import annotations

import base64
import binascii
from dataclasses import dataclass, field
from urllib.parse import parse_qs, urlparse


class MagnetError(ValueError):
    pass


@dataclass(frozen=True)
class Magnet:
    # v1 (btih, 20 bytes) and/or v2 (btmh sha2-256 multihash, 32 bytes)
    # exact topics; hybrid magnets carry both, pure-v2 only the latter
    info_hash: bytes | None = None
    display_name: str | None = None
    trackers: tuple[str, ...] = ()
    peer_addrs: tuple[tuple[str, int], ...] = field(default_factory=tuple)
    info_hash_v2: bytes | None = None

    def to_uri(self) -> str:
        topics = []
        if self.info_hash is not None:
            topics.append(f"xt=urn:btih:{self.info_hash.hex()}")
        if self.info_hash_v2 is not None:
            topics.append(f"xt=urn:btmh:1220{self.info_hash_v2.hex()}")
        if not topics:
            raise MagnetError("magnet needs at least one exact topic")
        parts = ["magnet:?" + topics[0]] + topics[1:]
        if self.display_name:
            from urllib.parse import quote

            parts.append(f"dn={quote(self.display_name)}")
        for tr in self.trackers:
            from urllib.parse import quote

            parts.append(f"tr={quote(tr, safe='')}")
        for host, port in self.peer_addrs:
            h = f"[{host}]" if ":" in host else host  # IPv6 re-bracketing
            parts.append(f"x.pe={h}:{port}")
        return "&".join(parts)


def _decode_btih(value: str) -> bytes:
    if len(value) == 40:
        try:
            return binascii.unhexlify(value)
        except binascii.Error as e:
            raise MagnetError(f"bad hex info hash {value!r}") from e
    if len(value) == 32:
        try:
            return base64.b32decode(value.upper())
        except binascii.Error as e:
            raise MagnetError(f"bad base32 info hash {value!r}") from e
    raise MagnetError(f"info hash must be 40 hex or 32 base32 chars, got {value!r}")


def parse_magnet(uri: str) -> Magnet:
    """Parse a magnet URI; raises ``MagnetError`` on anything malformed."""
    parsed = urlparse(uri)
    if parsed.scheme != "magnet":
        raise MagnetError(f"not a magnet URI: {uri!r}")
    params = parse_qs(parsed.query)
    info_hash = None
    info_hash_v2 = None
    for xt in params.get("xt", []):
        if xt.startswith("urn:btih:") and info_hash is None:
            info_hash = _decode_btih(xt[len("urn:btih:") :])
        elif xt.startswith("urn:btmh:") and info_hash_v2 is None:
            # BEP 52: sha2-256 multihash — 0x12 (sha2-256) 0x20 (32 bytes).
            # Unrecognized algos/shapes are SKIPPED, not fatal: a hybrid
            # magnet's btih topic must stay usable whatever rides beside it
            mh = xt[len("urn:btmh:") :]
            if len(mh) == 68 and mh.lower().startswith("1220"):
                try:
                    info_hash_v2 = binascii.unhexlify(mh[4:])
                except binascii.Error:
                    pass
    if info_hash is None and info_hash_v2 is None:
        raise MagnetError("magnet URI has no urn:btih/btmh exact topic")
    peers: list[tuple[str, int]] = []
    for pe in params.get("x.pe", []):
        host, _, port_s = pe.rpartition(":")
        try:
            port = int(port_s)
        except ValueError as e:
            raise MagnetError(f"bad x.pe address {pe!r}") from e
        if not host or not 0 < port < 65536:
            raise MagnetError(f"bad x.pe address {pe!r}")
        peers.append((host.strip("[]"), port))
    return Magnet(
        info_hash=info_hash,
        info_hash_v2=info_hash_v2,
        display_name=params["dn"][0] if params.get("dn") else None,
        trackers=tuple(params.get("tr", [])),
        peer_addrs=tuple(peers),
    )
