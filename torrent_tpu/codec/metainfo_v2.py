"""BitTorrent v2 (BEP 52) metainfo: file trees, piece layers, sha256 roots.

The reference is v1-only (`metainfo.ts` knows nothing of BEP 52) — this
module is beyond-parity surface. v2 replaces the flat ``pieces`` blob
with a per-file SHA-256 merkle tree:

- ``info["meta version"] = 2`` and ``info["file tree"]`` — a nested dict
  of path components; each file node is ``{b"": {length, pieces root}}``.
- top-level ``piece layers`` — for every file larger than one piece, the
  subtree roots at piece height, concatenated 32-byte digests keyed by
  the file's ``pieces root``.
- the v2 infohash is SHA-256 over the raw bencoded info span (truncated
  to 20 bytes on the wire where v1 compatibility demands it).

Pure codec here (parse/encode/validate); the batched hashing/verify
pipeline lives in ``models/v2.py``.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from torrent_tpu.codec.bencode import BencodeError, bdecode_with_info_span, bencode

SHA256_LEN = 32
BLOCK = 16384  # BEP 52 leaf block size


@dataclass(frozen=True)
class V2File:
    path: tuple[str, ...]
    length: int
    pieces_root: bytes  # 32-byte SHA-256 merkle root

    def num_pieces(self, piece_length: int) -> int:
        return max(1, -(-self.length // piece_length)) if self.length else 0


@dataclass(frozen=True)
class InfoDictV2:
    name: str
    piece_length: int
    files: tuple[V2File, ...]
    private: bool = False  # BEP 27 — inside info, affects the infohash

    @property
    def length(self) -> int:
        return sum(f.length for f in self.files)


@dataclass(frozen=True)
class MetainfoV2:
    announce: str | None
    info: InfoDictV2
    info_hash_v2: bytes  # 32-byte SHA-256 over the raw info span
    # file's pieces_root -> per-piece subtree roots (files > piece_length)
    piece_layers: dict[bytes, tuple[bytes, ...]] = field(repr=False, default_factory=dict)
    raw: dict = field(repr=False, default_factory=dict)

    @property
    def truncated_info_hash(self) -> bytes:
        """20-byte truncation used where v1-shaped infohashes are needed
        (tracker/DHT wire compatibility, BEP 52 §"infohash")."""
        return self.info_hash_v2[:20]


def valid_path_component(name: str) -> bool:
    """A BEP 52 path component: a plain UTF-8 name that cannot escape a
    target directory when joined."""
    if name in ("", ".", "..") or any(c in name for c in ("/", "\\", "\x00")):
        return False
    try:
        name.encode("utf-8")
    except UnicodeEncodeError:  # surrogateescape names from os.walk
        return False
    return True


def _walk_file_tree(node: dict, prefix: tuple[str, ...], out: list[V2File]) -> bool:
    """Depth-first over the nested ``file tree`` dict. Returns False on a
    malformed node (the whole parse then fails closed)."""
    for key, child in node.items():
        if not isinstance(key, bytes) or not isinstance(child, dict):
            return False
        if key == b"":
            return False  # a file marker may not appear amid siblings here
        name = key.decode("utf-8", "replace")
        # fail closed on hostile path components: anything that could
        # escape a target directory when joined rejects the whole torrent
        if not valid_path_component(name):
            return False
        marker = child.get(b"")
        if marker is not None:
            if set(child.keys()) != {b""} or not isinstance(marker, dict):
                return False
            length = marker.get(b"length")
            root = marker.get(b"pieces root")
            if not isinstance(length, int) or length < 0:
                return False
            if length > 0 and (not isinstance(root, bytes) or len(root) != SHA256_LEN):
                return False
            out.append(
                V2File(
                    path=prefix + (name,),
                    length=length,
                    pieces_root=root if isinstance(root, bytes) else b"\x00" * SHA256_LEN,
                )
            )
        else:
            if not _walk_file_tree(child, prefix + (name,), out):
                return False
    return True


def parse_v2_info_dict(info) -> InfoDictV2 | None:
    """Shape-validate a decoded BEP 52 info dict (bytes-keyed) alone.

    The info-only entry point for magnet joins, where the dict arrives
    via ut_metadata and the piece layers come separately over BEP 52
    hash transfer. Fail-closed: None on any malformation.
    """
    if not isinstance(info, dict) or info.get(b"meta version") != 2:
        return None
    name = info.get(b"name")
    plen = info.get(b"piece length")
    tree = info.get(b"file tree")
    if (
        not isinstance(name, bytes)
        or not isinstance(plen, int)
        or plen < BLOCK
        or plen & (plen - 1)  # must be a power of two (BEP 52)
        or not isinstance(tree, dict)
    ):
        return None
    files: list[V2File] = []
    if not _walk_file_tree(tree, (), files):
        return None
    return InfoDictV2(
        name=name.decode("utf-8", "replace"),
        piece_length=plen,
        files=tuple(files),
        private=info.get(b"private") == 1,
    )


def parse_metainfo_v2(data: bytes) -> MetainfoV2 | None:
    """Parse a v2 (or hybrid) .torrent; None on anything malformed.

    Mirrors the fail-closed contract of ``parse_metainfo``
    (metainfo.ts:145-147): no exceptions escape for bad input.
    """
    try:
        root, info_span = bdecode_with_info_span(data)
    except BencodeError:
        return None
    if not isinstance(root, dict) or info_span is None:
        return None
    span_start, span_end = info_span
    info = root.get(b"info")
    parsed_info = parse_v2_info_dict(info)
    if parsed_info is None:
        return None
    plen = parsed_info.piece_length
    files = parsed_info.files

    layers_raw = root.get(b"piece layers", {})
    if not isinstance(layers_raw, dict):
        return None
    piece_layers: dict[bytes, tuple[bytes, ...]] = {}
    for k, v in layers_raw.items():
        if (
            not isinstance(k, bytes)
            or len(k) != SHA256_LEN
            or not isinstance(v, bytes)
            or len(v) % SHA256_LEN
        ):
            return None
        piece_layers[k] = tuple(v[i : i + SHA256_LEN] for i in range(0, len(v), SHA256_LEN))

    # every multi-piece file must have its layer, with the right count
    for f in files:
        if f.length > plen:
            layer = piece_layers.get(f.pieces_root)
            if layer is None or len(layer) != f.num_pieces(plen):
                return None

    announce = root.get(b"announce")
    return MetainfoV2(
        announce=announce.decode("utf-8", "replace") if isinstance(announce, bytes) else None,
        info=parsed_info,
        info_hash_v2=hashlib.sha256(data[span_start:span_end]).digest(),
        piece_layers=piece_layers,
        raw=root,
    )


def encode_metainfo_v2(
    info: InfoDictV2,
    piece_layers: dict[bytes, tuple[bytes, ...]],
    announce: str | None = None,
    comment: str | None = None,
    announce_list: list[list[str]] | None = None,
    web_seeds: list[str] | None = None,
    v1_pieces: list[bytes] | None = None,
    v1_files: list[dict] | None = None,
    v1_length: int | None = None,
) -> bytes:
    """Bencode a v2 (or, with the ``v1_*`` fields, hybrid) .torrent.

    ``comment``/``announce_list`` (BEP 12) / ``web_seeds`` (BEP 19) are
    top-level fields exactly as in v1; ``info.private`` (BEP 27) goes
    inside the info dict so it is covered by the infohash. Passing
    ``v1_pieces`` plus ``v1_files`` (multi-file, with BEP 47 pad entries)
    or ``v1_length`` (single-file) adds the v1 generation's fields to the
    same info dict — the BEP 52 upgrade path, one blob both client
    generations read, two infohashes (sha1/sha256 of the same span).
    """
    tree: dict = {}
    for f in info.files:
        node = tree
        for part in f.path:
            node = node.setdefault(part.encode(), {})
        marker: dict = {b"length": f.length}
        if f.length > 0:
            marker[b"pieces root"] = f.pieces_root
        node[b""] = marker
    info_dict: dict = {
        b"meta version": 2,
        b"name": info.name.encode(),
        b"piece length": info.piece_length,
        b"file tree": tree,
    }
    if v1_pieces is not None:
        info_dict[b"pieces"] = b"".join(v1_pieces)
        if v1_files is not None:
            info_dict[b"files"] = v1_files
        else:
            info_dict[b"length"] = v1_length or 0
    if info.private:
        info_dict[b"private"] = 1
    root: dict = {b"info": info_dict}
    if piece_layers:
        root[b"piece layers"] = {
            k: b"".join(v) for k, v in piece_layers.items()
        }
    if announce:
        root[b"announce"] = announce.encode()
    if comment:
        root[b"comment"] = comment.encode()
    if announce_list:
        root[b"announce-list"] = [[t.encode() for t in tier] for tier in announce_list]
    if web_seeds:
        root[b"url-list"] = [u.encode() for u in web_seeds]
    return bencode(root)
