""".torrent metainfo parsing (reference layer L2: metainfo.ts, 148 LoC).

Parses and shape-validates a ``.torrent`` file into typed dataclasses:
normalizes ``piece length`` → ``piece_length``, splits the ``pieces`` blob
into 20-byte SHA1 digests (metainfo.ts:111), sums multi-file lengths
(metainfo.ts:125), and computes the BEP 3 infohash.

Infohash design note: the reference re-bencodes the decoded info dict and
hashes that (metainfo.ts:141-143), which only matches because its codec
preserves key order. Here the decoder reports the *byte span* of the raw
``info`` value (codec/bencode.py:bdecode_with_info_span) and the hash is
taken over the original bytes — correct for any foreign torrent regardless
of key order or duplicate quirks.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from torrent_tpu.codec import valid
from torrent_tpu.codec.bencode import BencodeError, bdecode, bdecode_with_info_span
from torrent_tpu.utils.bytesio import partition

SHA1_LEN = 20


def parse_url_list(ul) -> tuple[str, ...]:
    """BEP 19 ``url-list``: a single URL string or a list of them.

    Shared by the v1 ``Metainfo`` and v2 ``session.v2.V2SessionMeta``
    web_seeds properties — one normalization for both planes."""
    if isinstance(ul, bytes):
        ul = [ul]
    if not isinstance(ul, list):
        return ()
    return tuple(
        u.decode("utf-8", "replace") for u in ul if isinstance(u, bytes) and u
    )


@dataclass(frozen=True)
class FileEntry:
    """One file of a multi-file torrent (metainfo.ts MultiFileFields).

    ``pad`` marks a BEP 47 padding file (``attr`` contains ``p``): its
    bytes are zeros that exist only to piece-align the next real file
    (hybrid torrents always carry them). Pad spans occupy piece space
    but are never written to or read from disk (storage/storage.py).
    """

    length: int
    path: tuple[str, ...]  # path components, decoded UTF-8
    pad: bool = False


@dataclass(frozen=True)
class InfoDict:
    """Normalized info dict (metainfo.ts:44-60).

    ``files`` is None for single-file torrents; ``length`` is always the
    total payload size (summed for multi-file, metainfo.ts:125).
    """

    name: str
    piece_length: int
    pieces: tuple[bytes, ...]  # 20-byte SHA1 digests
    length: int
    files: tuple[FileEntry, ...] | None = None

    @property
    def num_pieces(self) -> int:
        return len(self.pieces)

    @property
    def is_multi_file(self) -> bool:
        return self.files is not None


@dataclass(frozen=True)
class Metainfo:
    """Parsed .torrent (metainfo.ts Metainfo)."""

    announce: str
    info: InfoDict
    info_hash: bytes  # 20-byte SHA1 over the raw bencoded info dict
    # Raw decoded top-level dict (bytes keys) for extra fields like
    # `comment`, `creation date`, `announce-list` — preserved, not dropped.
    raw: dict = field(repr=False, default_factory=dict)

    @property
    def web_seeds(self) -> tuple[str, ...]:
        """BEP 19 ``url-list`` (single string or list of strings)."""
        return parse_url_list(self.raw.get(b"url-list"))

    @property
    def http_seeds(self) -> tuple[str, ...]:
        """BEP 17 ``httpseeds`` — the older Hoffman-style HTTP seeding
        where the server speaks ``?info_hash=...&piece=N`` instead of
        byte-range file GETs."""
        return parse_url_list(self.raw.get(b"httpseeds"))

    @property
    def similar(self) -> tuple[bytes, ...]:
        """BEP 38 ``similar``: infohashes of torrents likely to share
        identical files with this one. Read from the info dict (where an
        author binds them into the infohash) and the top level (where a
        downstream publisher may add more); order-preserving union."""
        return parse_similar(self.raw)

    @property
    def update_url(self) -> str | None:
        """BEP 39 ``update-url``: where an updated version of this
        torrent can be fetched. Info-dict placement wins (infohash-bound
        — a middleman can't redirect updates without changing the
        identity); top-level accepted as the mutable fallback."""
        return parse_update_url(self.raw)

    @property
    def collections(self) -> tuple[str, ...]:
        """BEP 38 ``collections``: publisher-chosen group names; torrents
        sharing a collection are candidates for local-file reuse."""
        return parse_collections(self.raw)


def parse_any_metainfo(data: bytes):
    """``(meta, session_info_hash)`` for a v1 OR pure-v2 .torrent; None
    when neither format parses. The hash is each format's session
    identity — SHA-1, or BEP 52's truncated SHA-256 — i.e. what
    ``Client.add`` keys torrents by. One helper so the fetch-and-identify
    dance (BEP 39 update-url, BEP 36 feeds, CLI) can't drift apart."""
    m = parse_metainfo(data)
    if m is not None:
        return m, m.info_hash
    from torrent_tpu.codec.metainfo_v2 import parse_metainfo_v2

    v2 = parse_metainfo_v2(data)
    if v2 is None:
        return None
    return v2, v2.truncated_info_hash


def _hint_sources(raw: dict):
    info = raw.get(b"info")
    return ((info if isinstance(info, dict) else {}), raw)


def parse_similar(raw: dict) -> tuple[bytes, ...]:
    """BEP 38 ``similar`` from a decoded top-level dict (shared by the v1
    ``Metainfo`` and the v2 session wrapper): info placement first, then
    top level, deduped in order."""
    out: list[bytes] = []
    for src in _hint_sources(raw):
        v = src.get(b"similar")
        if isinstance(v, list):
            for h in v:
                if isinstance(h, bytes) and len(h) in (20, 32) and h not in out:
                    out.append(h)
    return tuple(out)


def parse_collections(raw: dict) -> tuple[str, ...]:
    """BEP 38 ``collections`` from a decoded top-level dict."""
    out: list[str] = []
    for src in _hint_sources(raw):
        v = src.get(b"collections")
        if isinstance(v, list):
            for c in v:
                if isinstance(c, bytes):
                    s = c.decode("utf-8", "replace")
                    if s and s not in out:
                        out.append(s)
    return tuple(out)


def parse_update_url(raw: dict) -> str | None:
    """BEP 39 ``update-url`` from a decoded top-level dict; info-dict
    placement wins over top level."""
    for src in _hint_sources(raw):
        v = src.get(b"update-url")
        if isinstance(v, bytes) and v:
            return v.decode("utf-8", "replace")
    return None


_FILE_SHAPE = valid.obj(
    {
        b"length": valid.num(),
        b"path": valid.arr(valid.bstr()),
    }
)

_INFO_SHAPE = valid.obj(
    {
        b"name": valid.bstr(),
        b"piece length": valid.num(),
        b"pieces": valid.multiple_len_bytes(SHA1_LEN),
        b"length": valid.optional(valid.num()),
        b"files": valid.optional(valid.arr(_FILE_SHAPE)),
    }
)

_METAINFO_SHAPE = valid.obj(
    {
        b"announce": valid.bstr(),
        b"info": _INFO_SHAPE,
    }
)


def parse_metainfo(data: bytes) -> Metainfo | None:
    """Parse .torrent bytes; returns None on any failure (metainfo.ts:145-147).

    Exactly one of ``info.length`` / ``info.files`` must be present
    (single- vs multi-file mode); geometry is sanity-checked: the digest
    count must match ``ceil(length / piece_length)``.
    """
    try:
        decoded, info_span = bdecode_with_info_span(data)
    except BencodeError:
        return None
    if not _METAINFO_SHAPE(decoded):
        return None
    raw_info = decoded[b"info"]
    has_length = raw_info.get(b"length") is not None
    has_files = raw_info.get(b"files") is not None
    if has_length == has_files:  # both or neither
        return None
    if info_span is None:
        return None

    try:
        name = raw_info[b"name"].decode("utf-8")
    except UnicodeDecodeError:
        return None
    piece_length = raw_info[b"piece length"]
    if piece_length <= 0:
        return None
    pieces = tuple(partition(raw_info[b"pieces"], SHA1_LEN))

    files: tuple[FileEntry, ...] | None = None
    if has_files:
        entries = []
        total = 0
        for f in raw_info[b"files"]:
            if f[b"length"] < 0 or not f[b"path"]:
                return None
            try:
                path = tuple(p.decode("utf-8") for p in f[b"path"])
            except UnicodeDecodeError:
                return None
            attr = f.get(b"attr")
            entries.append(
                FileEntry(
                    length=f[b"length"],
                    path=path,
                    # BEP 47: attr is a string of flag chars; 'p' = pad
                    pad=isinstance(attr, bytes) and b"p" in attr,
                )
            )
            total += f[b"length"]
        files = tuple(entries)
        length = total
    else:
        length = raw_info[b"length"]
        if length < 0:
            return None

    expected_pieces = (length + piece_length - 1) // piece_length
    if expected_pieces != len(pieces):
        return None

    try:
        announce = decoded[b"announce"].decode("utf-8")
    except UnicodeDecodeError:
        return None

    start, end = info_span
    info_hash = hashlib.sha1(data[start:end]).digest()

    return Metainfo(
        announce=announce,
        info=InfoDict(
            name=name,
            piece_length=piece_length,
            pieces=pieces,
            length=length,
            files=files,
        ),
        info_hash=info_hash,
        raw=decoded,
    )


def metainfo_from_info_bytes(
    info_bytes: bytes, announce: str = "", announce_list: list[list[str]] | None = None
) -> Metainfo | None:
    """Build a full ``Metainfo`` from a bare serialized info dict.

    The magnet-link path (BEP 9): after ut_metadata delivers the verified
    info-dict bytes, wrap them in a minimal torrent envelope. The
    re-encode of the decoded dict is byte-exact (decode preserves key
    order), so the computed ``info_hash`` matches ``sha1(info_bytes)``.
    """
    from torrent_tpu.codec.bencode import bencode

    envelope: dict = {b"announce": announce.encode("utf-8")}
    if announce_list:
        envelope[b"announce-list"] = [
            [t.encode("utf-8") for t in tier] for tier in announce_list
        ]
    try:
        envelope[b"info"] = bdecode(info_bytes)
    except BencodeError:
        return None
    return parse_metainfo(bencode(envelope, sort_keys=False))
