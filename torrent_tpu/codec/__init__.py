from torrent_tpu.codec.bencode import (
    bencode,
    bdecode,
    bdecode_with_info_span,
    BencodeError,
)
from torrent_tpu.codec.metainfo import parse_metainfo, Metainfo, InfoDict, FileEntry

__all__ = [
    "bencode",
    "bdecode",
    "bdecode_with_info_span",
    "BencodeError",
    "parse_metainfo",
    "Metainfo",
    "InfoDict",
    "FileEntry",
]
