"""``python -m torrent_tpu`` → the proof-of-concept CLI (tools/cli.py)."""

import sys

from torrent_tpu.tools.cli import main

sys.exit(main())
