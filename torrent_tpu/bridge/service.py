"""Localhost HTTP bridge to the TPU hash plane.

The BASELINE north star's topology: a non-Python BitTorrent client (e.g.
the reference's Deno runtime) streams piece buffers to a local JAX
sidecar and gets digests/verdicts back. Wire format is bencode — the one
codec every BitTorrent client already has:

  POST /v1/digests   body {pieces: [bytes, ...]}
                     → {digests: [20-byte sha1, ...]}
  POST /v1/verify    body {pieces: [bytes, ...], expected: [20B, ...]}
                     → {ok: bytes}            (one 0x00/0x01 per piece)
  GET  /v1/info      → {backend, devices, batch} (capability probe)

Streaming ingest (the north-star topology: a Deno client pushing a
100 GiB recheck must not need 100 GiB — or even 1 GiB — resident in the
sidecar). The client declares the torrent's piece length in an
``X-Piece-Length`` header and streams length-prefixed frames; the
sidecar consumes them straight into the verifier's staging buffers,
flushing a device batch every ``batch_size`` pieces. Resident memory is
two staging buffers (~2 × batch × padded_len), independent of body size.
Bodies may be Content-Length or chunked transfer-encoding (what a Deno
``fetch`` with a ReadableStream body produces).

  POST /v1/stream/digests   frames: u32be(len) | piece
                            → {digests: [20B, ...]}
  POST /v1/stream/verify    frames: u32be(len) | piece | 20B expected
                            → {ok: bytes, valid: int}

An ``X-Hash-Algo: sha256`` header switches the stream routes to the v2
hash plane (BEP 52 leaf/merkle hashing feeds on 32-byte digests); the
default is sha1. Digest/expected width follows the algorithm.

Hand-rolled asyncio HTTP — no web framework needed for five routes.
"""

from __future__ import annotations

import asyncio
import threading

from torrent_tpu.codec.bencode import BencodeError, bdecode, bencode
from torrent_tpu.utils.log import get_logger

log = get_logger("bridge")

MAX_BODY = 1 << 30  # 1 GiB of piece data per buffered (non-stream) request
# Cap on one streamed frame. 16 MiB is the practical BitTorrent piece-size
# ceiling, and it keeps the staging-budget invariant honest even after
# TPUVerifier rounds batch_size up to the mesh size: worst case is
# 2 slots × max(batch, mesh) rows × ~16 MiB = 256 MiB on an 8-device mesh.
MAX_PIECE = 16 << 20
# An endless frame stream must not grow the result lists without bound:
# 4M frames ≈ 80 MB of digests ≈ a 1 TiB torrent at 256 KiB pieces.
MAX_STREAM_FRAMES = 1 << 22
FRAME_TIMEOUT = 60.0  # idle seconds between frame reads before dropping


class _BodyReader:
    """Incremental body reader: Content-Length or chunked transfer-encoding.

    Exposes ``read_upto(n)`` over the framed body and ``at_eof()`` once
    the body is fully consumed — never holds more than one read's worth
    of bytes beyond the StreamReader's own buffer.
    """

    def __init__(self, reader: asyncio.StreamReader, headers: dict[bytes, bytes]):
        self._r = reader
        te = headers.get(b"transfer-encoding", b"").lower()
        self._chunked = b"chunked" in te
        try:
            self._remaining = int(headers.get(b"content-length", b"0") or 0)
        except ValueError:
            self._remaining = 0
        self._chunk_left = 0  # bytes left in the current chunk (chunked mode)
        self._done = not self._chunked and self._remaining == 0

    async def _next_chunk(self) -> None:
        size_line = await self._r.readline()
        # tolerate the CRLF terminating the previous chunk
        while size_line in (b"\r\n", b"\n"):
            size_line = await self._r.readline()
        if size_line == b"":
            # connection cut mid-body: a truncated chunked stream must NOT
            # read as clean EOF (a 200 over partial frames would be taken
            # as a completed recheck)
            raise asyncio.IncompleteReadError(b"", None)
        size = int(size_line.split(b";", 1)[0].strip(), 16)
        if size == 0:
            # trailer section until blank line
            while True:
                line = await self._r.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            self._done = True
        self._chunk_left = size

    async def read_upto(self, n: int) -> bytes:
        """Up to ``n`` body bytes; b"" at EOF."""
        if self._done:
            return b""
        if self._chunked:
            if self._chunk_left == 0:
                await self._next_chunk()
                if self._done:
                    return b""
            take = min(n, self._chunk_left)
            data = await self._r.readexactly(take)
            self._chunk_left -= take
            return data
        take = min(n, self._remaining)
        data = await self._r.readexactly(take)
        self._remaining -= take
        if self._remaining == 0:
            self._done = True
        return data

    async def at_eof(self) -> bool:
        if self._done:
            return True
        if self._chunked and self._chunk_left == 0:
            await self._next_chunk()
            return self._done
        return False


class BridgeServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, hasher: str = "tpu"):
        self.host = host
        self.port = port
        self.hasher = hasher
        self._server: asyncio.AbstractServer | None = None
        self._verifiers: dict[int, object] = {}
        self._verifiers_lock = threading.Lock()
        self._stream_gate: asyncio.Semaphore | None = None

    async def start(self) -> "BridgeServer":
        # at most 4 concurrent streaming ingests hold staging buffers;
        # further streams wait instead of multiplying resident memory
        self._stream_gate = asyncio.Semaphore(4)
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("bridge listening on %s:%d", self.host, self.port)
        return self

    def close(self) -> None:
        if self._server:
            self._server.close()

    async def wait_closed(self) -> None:
        if self._server:
            await self._server.wait_closed()

    # ------------------------------------------------------------ hashing

    def _digests(self, pieces: list[bytes]) -> list[bytes]:
        if self.hasher == "cpu":
            import hashlib

            return [hashlib.sha1(p).digest() for p in pieces]
        cap = max((len(p) for p in pieces), default=64)
        return self._stream_verifier(cap).hash_pieces(pieces)

    # ~128 MiB per staging buffer regardless of piece size; the batch
    # shrinks as pieces grow so a hostile X-Piece-Length can't OOM the
    # sidecar (2 slots × budget ≈ 256 MiB peak, worst case one 64 MiB row
    # per slot).
    STAGING_BUDGET = 128 << 20

    def _bucket_and_batch(self, plen: int) -> tuple[int, int]:
        """Pow-2 piece-length bucket + the batch the staging budget affords."""
        from torrent_tpu.ops.padding import padded_len_for

        bucket = 1 << (plen - 1).bit_length() if plen > 1 else 1
        batch = max(1, min(256, self.STAGING_BUDGET // padded_len_for(bucket)))
        return bucket, batch

    def _stream_verifier(self, plen: int):
        """Verifier for the given piece length — pow-2 bucketed so a
        handful of executables serve any geometry (shared by the buffered
        and streaming routes)."""
        from torrent_tpu.models.verifier import TPUVerifier

        bucket, batch = self._bucket_and_batch(plen)
        # callers run on both the event loop and to_thread workers; the
        # lock keeps a bucket from being built (and compiled) twice
        with self._verifiers_lock:
            verifier = self._verifiers.get(bucket)
            if verifier is None:
                verifier = TPUVerifier(piece_length=bucket, batch_size=batch)
                self._verifiers[bucket] = verifier
        return verifier

    # ----------------------------------------------------------- streaming

    async def _route_stream(self, writer, target: str, headers, body: _BodyReader):
        """Length-prefixed frame ingest with bounded resident memory.

        Frames land directly in the verifier's staging buffers; a device
        batch is flushed every ``batch_size`` pieces on a worker thread
        while the event loop keeps ingesting into the other buffer
        (``verify_batch``/``digest_batch`` return only after the staging
        buffer is fully uploaded, so reuse after the flush future resolves
        is safe). Peak memory ≈ 2 staging buffers, independent of body size.
        """
        mode = target.rsplit("/", 1)[-1]
        if mode not in ("digests", "verify"):
            return await self._reply(writer, 404, b"not found")
        try:
            plen = int(headers.get(b"x-piece-length", b"0") or 0)
        except ValueError:
            plen = 0
        if plen <= 0 or plen > MAX_PIECE:
            return await self._reply(writer, 400, b"X-Piece-Length required (1..16MiB)")
        algo = headers.get(b"x-hash-algo", b"sha1").decode("latin-1").lower()
        if algo not in ("sha1", "sha256"):
            return await self._reply(writer, 400, b"X-Hash-Algo must be sha1 or sha256")

        if self.hasher == "cpu":
            return await self._stream_cpu(writer, mode, plen, body, algo)
        async with self._stream_gate:
            await self._stream_tpu(writer, mode, plen, body, algo)

    @staticmethod
    async def _read_idle_bounded(body: _BodyReader, n: int) -> bytes:
        """``readexactly(n)`` where the timeout bounds *idle* time, not
        total transfer time — each successful chunk resets the clock, so a
        slow-but-live client streaming a big piece is never dropped."""
        parts, got = [], 0
        while got < n:
            chunk = await asyncio.wait_for(
                body.read_upto(min(n - got, 1 << 18)), FRAME_TIMEOUT
            )
            if not chunk:
                raise asyncio.IncompleteReadError(b"".join(parts), n)
            parts.append(chunk)
            got += len(chunk)
        return b"".join(parts)

    async def _read_frame(
        self, body: _BodyReader, plen: int, with_expected: bool, digest_len: int = 20
    ):
        """One ``len | piece [| expected]`` frame, or None at clean EOF.

        Reads are idle-bounded so a silent client can't pin staging
        buffers forever. Raises ValueError on an oversized frame.
        """
        if await asyncio.wait_for(body.at_eof(), FRAME_TIMEOUT):
            return None
        ln = int.from_bytes(await self._read_idle_bounded(body, 4), "big")
        if ln > plen:
            raise ValueError("frame exceeds X-Piece-Length")
        data = await self._read_idle_bounded(body, ln)
        expected = (
            await self._read_idle_bounded(body, digest_len) if with_expected else None
        )
        return data, expected

    def _stream_plane256(self, plen: int):
        """Minimal SHA-256 batch plane for the stream routes (v2 digests
        use 32-byte words; the sha1 TPUVerifier's on-device compare and
        flat-upload machinery don't apply — digest words come back host-
        side and compare there, [B, 8] u32 per batch is tiny)."""
        from torrent_tpu.ops.sha256_jax import make_sha256_fn

        bucket, batch = self._bucket_and_batch(plen)
        key = ("sha256", bucket)
        with self._verifiers_lock:
            plane = self._verifiers.get(key)
            if plane is None:
                import jax

                # always the scan backend: sha256_pieces_pallas pads every
                # launch to a tile_sub*128-row multiple (>=1024), which
                # would blow the staging budget this batch size exists to
                # enforce (a 16 MiB bucket would balloon on device)
                fn = make_sha256_fn("jax")

                class _Plane:
                    piece_length = bucket
                    batch_size = batch

                    @staticmethod
                    def digest_words(padded, nblocks):
                        import numpy as np

                        return np.asarray(fn(jax.numpy.asarray(padded), jax.numpy.asarray(nblocks)))

                plane = _Plane()
                self._verifiers[key] = plane
        return plane

    async def _stream_tpu(self, writer, mode: str, plen: int, body: _BodyReader, algo: str):
        import concurrent.futures

        import numpy as np

        from torrent_tpu.models.merkle import digests_to_words32, words32_to_digests
        from torrent_tpu.ops.padding import (
            alloc_padded,
            digests_to_words,
            pad_in_place,
            words_to_digests,
        )

        # verifier construction (JAX init, jit setup) and the ~128 MiB slot
        # memsets run off the event loop so health probes and other
        # connections stay live through them
        if algo == "sha256":
            verifier = await asyncio.to_thread(self._stream_plane256, plen)
            dlen, words_dim = 32, 8
            to_words = lambda d: digests_to_words32([d])[0]
        else:
            verifier = await asyncio.to_thread(self._stream_verifier, plen)
            dlen, words_dim = 20, 5
            to_words = lambda d: digests_to_words([d])[0]
        b = verifier.batch_size
        slots: list[dict] = []  # allocated lazily on the first frame

        def make_slot():
            padded, view = alloc_padded(b, verifier.piece_length)
            return {
                "padded": padded,
                "view": view,
                "lengths": np.zeros(b, dtype=np.int64),
                "expected": np.zeros((b, words_dim), dtype=np.uint32),
            }

        loop = asyncio.get_running_loop()
        flusher = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        pending: list = []  # in-order flush futures
        digests: list[bytes] = []
        ok_flags = bytearray()

        def flush(slot, k):
            nblocks = pad_in_place(slot["padded"], slot["lengths"])
            nblocks[k:] = 0
            if algo == "sha256":
                words = verifier.digest_words(slot["padded"], nblocks)
                if mode == "digests":
                    return words32_to_digests(words[:k])
                ok = (words[:k] == slot["expected"][:k]).all(axis=1)
                return bytes(ok.astype(np.uint8))
            if mode == "digests":
                words = verifier.digest_batch(slot["padded"], nblocks)
                return words_to_digests(words[:k])
            ok = verifier.verify_batch(slot["padded"], nblocks, slot["expected"])
            return bytes(ok[:k].astype(np.uint8))

        def collect(res):
            if mode == "digests":
                digests.extend(res)
            else:
                ok_flags.extend(res)

        try:
            slot_idx, k, n_frames = 0, 0, 0
            while True:
                frame = await self._read_frame(body, plen, mode == "verify", digest_len=dlen)
                if frame is None:
                    break
                n_frames += 1
                if n_frames > MAX_STREAM_FRAMES:
                    return await self._reply(writer, 413, b"too many frames")
                data, exp = frame
                if not slots:
                    slots = await asyncio.to_thread(lambda: [make_slot(), make_slot()])
                slot = slots[slot_idx]
                ln = len(data)
                slot["padded"][k, ln:] = 0  # clear stale pad bytes from last use
                slot["view"][k, :ln] = np.frombuffer(data, dtype=np.uint8)
                slot["lengths"][k] = ln
                if exp is not None:
                    slot["expected"][k] = to_words(exp)
                k += 1
                if k == b:
                    pending.append(loop.run_in_executor(flusher, flush, slot, k))
                    slot_idx, k = 1 - slot_idx, 0
                    if len(pending) == 2:
                        collect(await pending.pop(0))
            if k:
                pending.append(loop.run_in_executor(flusher, flush, slots[slot_idx], k))
            for fut in pending:
                collect(await fut)
            if mode == "digests":
                payload = bencode({b"digests": digests})
            else:
                payload = bencode({b"ok": bytes(ok_flags), b"valid": sum(ok_flags)})
            await self._reply(writer, 200, payload)
        except ValueError as e:
            await self._reply(writer, 400, str(e).encode())
        finally:
            flusher.shutdown(wait=False)

    async def _stream_cpu(self, writer, mode: str, plen: int, body: _BodyReader, algo: str = "sha1"):
        """hashlib fallback for ``hasher='cpu'``.

        Frames are hashed off the event loop in batches (≤64 frames or
        8 MiB) so neither thread-hop overhead per small piece nor a long
        inline hash of a big piece stalls concurrent connections.
        """
        import hashlib

        digests: list[bytes] = []
        ok_flags = bytearray()
        batch: list[bytes] = []
        batch_exp: list[bytes] = []
        batch_bytes = 0
        n_frames = 0

        hfn = hashlib.sha256 if algo == "sha256" else hashlib.sha1

        async def do_flush():
            nonlocal batch, batch_exp, batch_bytes
            ds = await asyncio.to_thread(
                lambda ps: [hfn(p).digest() for p in ps], batch
            )
            if mode == "digests":
                digests.extend(ds)
            else:
                ok_flags.extend(1 if d == e else 0 for d, e in zip(ds, batch_exp))
            batch, batch_exp, batch_bytes = [], [], 0

        try:
            while True:
                frame = await self._read_frame(
                    body, plen, mode == "verify",
                    digest_len=32 if algo == "sha256" else 20,
                )
                if frame is None:
                    break
                n_frames += 1
                if n_frames > MAX_STREAM_FRAMES:
                    return await self._reply(writer, 413, b"too many frames")
                data, exp = frame
                batch.append(data)
                batch_bytes += len(data)
                if exp is not None:
                    batch_exp.append(exp)
                if len(batch) >= 64 or batch_bytes >= (8 << 20):
                    await do_flush()
            if batch:
                await do_flush()
        except ValueError as e:
            return await self._reply(writer, 400, str(e).encode())
        if mode == "digests":
            payload = bencode({b"digests": digests})
        else:
            payload = bencode({b"ok": bytes(ok_flags), b"valid": sum(ok_flags)})
        await self._reply(writer, 200, payload)

    # --------------------------------------------------------------- http

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = (await asyncio.wait_for(reader.readline(), 60)).split()
            if len(request_line) < 2:
                return await self._reply(writer, 400, b"bad request")
            method, target = request_line[0].decode(), request_line[1].decode()
            headers: dict[bytes, bytes] = {}
            header_bytes = 0
            while True:
                line = await asyncio.wait_for(reader.readline(), 60)
                if line in (b"\r\n", b"\n", b""):
                    break
                header_bytes += len(line)
                if header_bytes > (16 << 10):  # endless header lines ≠ a request
                    return await self._reply(writer, 431, b"headers too large")
                if b":" in line:
                    k, v = line.split(b":", 1)
                    headers[k.strip().lower()] = v.strip()
            if method == "POST" and target.startswith("/v1/stream/"):
                body_reader = _BodyReader(reader, headers)
                return await self._route_stream(writer, target, headers, body_reader)
            try:
                content_length = int(headers.get(b"content-length", b"0") or 0)
            except ValueError:
                return await self._reply(writer, 400, b"bad content-length")
            if content_length > MAX_BODY:
                return await self._reply(writer, 413, b"body too large")
            body = await reader.readexactly(content_length) if content_length else b""
            await self._route(writer, method, target, body, headers)
        except (asyncio.TimeoutError, asyncio.IncompleteReadError, ConnectionError, OSError):
            writer.close()
        except Exception as e:  # one bad request must not kill the sidecar
            log.error("bridge error: %s", e)
            await self._reply(writer, 500, str(e).encode())

    async def _route(self, writer, method: str, target: str, body: bytes, headers=None):
        if method == "GET" and target == "/v1/info":
            import jax

            payload = bencode(
                {
                    b"backend": self.hasher.encode(),
                    b"devices": len(jax.devices()),
                    b"version": b"torrent-tpu/0.1",
                }
            )
            return await self._reply(writer, 200, payload)
        if method != "POST":
            return await self._reply(writer, 405, b"method not allowed")
        # the buffered hash routes are sha1-only; a sha256 request must
        # fail closed, not silently return v1 digests with a 200 (the
        # algorithm-agnostic /v1/info above is exempt)
        algo = (headers or {}).get(b"x-hash-algo", b"sha1").decode("latin-1").lower()
        if algo != "sha1":
            return await self._reply(
                writer, 400, b"buffered routes are sha1-only; use /v1/stream/* for sha256"
            )
        try:
            req = bdecode(body)
        except BencodeError as e:
            return await self._reply(writer, 400, f"bad bencode: {e}".encode())
        if not isinstance(req, dict) or not isinstance(req.get(b"pieces"), list):
            return await self._reply(writer, 400, b"missing pieces list")
        pieces = req[b"pieces"]
        if not all(isinstance(p, bytes) for p in pieces):
            return await self._reply(writer, 400, b"pieces must be bytestrings")
        if any(len(p) > MAX_PIECE for p in pieces):
            # same cap as the stream routes: an oversized piece would build
            # (and cache) a verifier bucket far beyond the staging budget
            return await self._reply(writer, 413, b"piece exceeds 16MiB cap")

        if target == "/v1/digests":
            digests = await asyncio.to_thread(self._digests, pieces)
            return await self._reply(writer, 200, bencode({b"digests": digests}))
        if target == "/v1/verify":
            expected = req.get(b"expected")
            if (
                not isinstance(expected, list)
                or len(expected) != len(pieces)
                or not all(isinstance(e, bytes) and len(e) == 20 for e in expected)
            ):
                return await self._reply(writer, 400, b"expected must be 20-byte hashes")
            digests = await asyncio.to_thread(self._digests, pieces)
            ok = bytes(
                1 if d == e else 0 for d, e in zip(digests, expected)
            )
            return await self._reply(writer, 200, bencode({b"ok": ok}))
        await self._reply(writer, 404, b"not found")

    async def _reply(self, writer, status: int, body: bytes):
        try:
            head = (
                f"HTTP/1.1 {status} X\r\nContent-Type: application/octet-stream\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            )
            writer.write(head.encode("latin-1") + body)
            await writer.drain()
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()


async def serve_bridge(host: str = "127.0.0.1", port: int = 8421, hasher: str = "tpu") -> BridgeServer:
    return await BridgeServer(host, port, hasher).start()


def main(argv=None):  # pragma: no cover - manual entrypoint
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8421)
    parser.add_argument("--hasher", choices=("cpu", "tpu"), default="tpu")
    args = parser.parse_args(argv)

    async def go():
        server = await serve_bridge(args.host, args.port, args.hasher)
        print(f"bridge listening on {args.host}:{server.port}")
        await server.wait_closed()

    asyncio.run(go())
    return 0


if __name__ == "__main__":  # pragma: no cover
    main()
