"""Localhost HTTP bridge to the TPU hash plane.

The BASELINE north star's topology: a non-Python BitTorrent client (e.g.
the reference's Deno runtime) streams piece buffers to a local JAX
sidecar and gets digests/verdicts back. Wire format is bencode — the one
codec every BitTorrent client already has:

  POST /v1/digests   body {pieces: [bytes, ...]}
                     → {digests: [20-byte sha1, ...]}
  POST /v1/verify    body {pieces: [bytes, ...], expected: [20B, ...]}
                     → {ok: bytes}            (one 0x00/0x01 per piece)
  GET  /v1/info      → {backend, devices, batch} (capability probe)

Hand-rolled asyncio HTTP (one round-trip, large bodies, Content-Length
framing) — no web framework needed for three routes.
"""

from __future__ import annotations

import asyncio

from torrent_tpu.codec.bencode import BencodeError, bdecode, bencode
from torrent_tpu.utils.log import get_logger

log = get_logger("bridge")

MAX_BODY = 1 << 30  # 1 GiB of piece data per request


class BridgeServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, hasher: str = "tpu"):
        self.host = host
        self.port = port
        self.hasher = hasher
        self._server: asyncio.AbstractServer | None = None
        self._verifiers: dict[int, object] = {}

    async def start(self) -> "BridgeServer":
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("bridge listening on %s:%d", self.host, self.port)
        return self

    def close(self) -> None:
        if self._server:
            self._server.close()

    async def wait_closed(self) -> None:
        if self._server:
            await self._server.wait_closed()

    # ------------------------------------------------------------ hashing

    def _digests(self, pieces: list[bytes]) -> list[bytes]:
        if self.hasher == "cpu":
            import hashlib

            return [hashlib.sha1(p).digest() for p in pieces]
        from torrent_tpu.models.verifier import TPUVerifier

        cap = max((len(p) for p in pieces), default=64)
        # bucket by next power of two so a handful of executables serve
        # any piece geometry
        bucket = 1 << (cap - 1).bit_length() if cap > 1 else 1
        verifier = self._verifiers.get(bucket)
        if verifier is None:
            verifier = TPUVerifier(piece_length=bucket, batch_size=256)
            self._verifiers[bucket] = verifier
        return verifier.hash_pieces(pieces)

    # --------------------------------------------------------------- http

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = (await asyncio.wait_for(reader.readline(), 60)).split()
            if len(request_line) < 2:
                return await self._reply(writer, 400, b"bad request")
            method, target = request_line[0].decode(), request_line[1].decode()
            content_length = 0
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                if line.lower().startswith(b"content-length:"):
                    content_length = int(line.split(b":", 1)[1])
            if content_length > MAX_BODY:
                return await self._reply(writer, 413, b"body too large")
            body = await reader.readexactly(content_length) if content_length else b""
            await self._route(writer, method, target, body)
        except (asyncio.TimeoutError, asyncio.IncompleteReadError, ConnectionError, OSError):
            writer.close()
        except Exception as e:  # one bad request must not kill the sidecar
            log.error("bridge error: %s", e)
            await self._reply(writer, 500, str(e).encode())

    async def _route(self, writer, method: str, target: str, body: bytes):
        if method == "GET" and target == "/v1/info":
            import jax

            payload = bencode(
                {
                    b"backend": self.hasher.encode(),
                    b"devices": len(jax.devices()),
                    b"version": b"torrent-tpu/0.1",
                }
            )
            return await self._reply(writer, 200, payload)
        if method != "POST":
            return await self._reply(writer, 405, b"method not allowed")
        try:
            req = bdecode(body)
        except BencodeError as e:
            return await self._reply(writer, 400, f"bad bencode: {e}".encode())
        if not isinstance(req, dict) or not isinstance(req.get(b"pieces"), list):
            return await self._reply(writer, 400, b"missing pieces list")
        pieces = req[b"pieces"]
        if not all(isinstance(p, bytes) for p in pieces):
            return await self._reply(writer, 400, b"pieces must be bytestrings")

        if target == "/v1/digests":
            digests = await asyncio.to_thread(self._digests, pieces)
            return await self._reply(writer, 200, bencode({b"digests": digests}))
        if target == "/v1/verify":
            expected = req.get(b"expected")
            if (
                not isinstance(expected, list)
                or len(expected) != len(pieces)
                or not all(isinstance(e, bytes) and len(e) == 20 for e in expected)
            ):
                return await self._reply(writer, 400, b"expected must be 20-byte hashes")
            digests = await asyncio.to_thread(self._digests, pieces)
            ok = bytes(
                1 if d == e else 0 for d, e in zip(digests, expected)
            )
            return await self._reply(writer, 200, bencode({b"ok": ok}))
        await self._reply(writer, 404, b"not found")

    async def _reply(self, writer, status: int, body: bytes):
        try:
            head = (
                f"HTTP/1.1 {status} X\r\nContent-Type: application/octet-stream\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            )
            writer.write(head.encode("latin-1") + body)
            await writer.drain()
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()


async def serve_bridge(host: str = "127.0.0.1", port: int = 8421, hasher: str = "tpu") -> BridgeServer:
    return await BridgeServer(host, port, hasher).start()


def main(argv=None):  # pragma: no cover - manual entrypoint
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8421)
    parser.add_argument("--hasher", choices=("cpu", "tpu"), default="tpu")
    args = parser.parse_args(argv)

    async def go():
        server = await serve_bridge(args.host, args.port, args.hasher)
        print(f"bridge listening on {args.host}:{server.port}")
        await server.wait_closed()

    asyncio.run(go())
    return 0


if __name__ == "__main__":  # pragma: no cover
    main()
