"""Localhost HTTP bridge to the TPU hash plane.

The BASELINE north star's topology: a non-Python BitTorrent client (e.g.
the reference's Deno runtime) streams piece buffers to a local JAX
sidecar and gets digests/verdicts back. Wire format is bencode — the one
codec every BitTorrent client already has:

  POST /v1/digests   body {pieces: [bytes, ...]}
                     → {digests: [20-byte sha1, ...]}
  POST /v1/verify    body {pieces: [bytes, ...], expected: [20B, ...]}
                     → {ok: bytes}            (one 0x00/0x01 per piece)
  GET  /v1/info      → {backend, devices, batch} (capability probe)
  GET  /metrics      → scheduler queue/fill/shed counters + per-stage
                       latency histograms (Prometheus text format 0.0.4)
  GET  /v1/trace     → JSON: ?id=<trace> the ordered span tree for that
                       trace; without id, the flight recorder's black-
                       box dumps + known trace ids (torrent_tpu/obs)
  GET  /v1/pipeline  → JSON: the pipeline ledger's per-stage snapshot
                       (recv → read → stage → h2d → launch → digest →
                       verdict)
                       plus the bottleneck attributor's verdict — which
                       stage limits the pipeline, achieved vs demanded
                       rate (obs/ledger + obs/attrib; `torrent-tpu top`
                       renders this live)
  GET  /v1/control   → JSON: the scheduler autopilot's last decision,
                       the inputs it saw, and every actuator's current
                       value (sched/control.py; `--autopilot` arms
                       actuation, otherwise the route reports the
                       controller as absent)
  GET  /v1/timeline  → JSON: the bounded ring of periodic obs samples
                       (obs/timeline; `--slo` arms the off-loop
                       sampler), dumpable to TORRENT_TPU_TIMELINE_DIR
                       and replayable offline via `torrent-tpu replay`
  GET  /v1/slo       → JSON: declared objectives, error-budget burn
                       rates (multi-window fast/slow classification),
                       budget remaining, breach state (obs/slo)
  GET  /v1/health    → JSON: liveness + readiness for a load balancer —
                       200 only when the backend probe resolved, no
                       breaker is stuck open past cooldown, the sampler
                       is alive, and no SLO objective is in breach
                       (503 with reasons otherwise)
  GET  /v1/swarm     → JSON: the swarm wire plane's bounded per-peer
                       telemetry (obs/swarm): top-K peers + overflow
                       fold, per-peer message/byte accounting, choke
                       timelines, block-RTT p50/p99, snub and
                       endgame-cancel counters, announce health
                       (`torrent-tpu top --swarm` renders it live; the
                       session MetricsServer answers the same route)

Every request runs under a trace span: an ``X-Trace-Id`` request header
is honored (well-formed tokens only) or a fresh id is minted, the id is
echoed back in the response, and the scheduler threads it through the
ticket lifecycle (enqueue → admission/shed → lane wait → launch/retry/
bisect → digest → verdict) so ``/v1/trace?id=…`` shows where a request
spent its time.

  POST /v1/fabric/verify  body {items: [{torrent, root}, ...]}
                          → 202; starts a scheduler-fed library recheck
                            (torrent_tpu/fabric) of sidecar-local paths
  GET  /v1/fabric/status  → {state, fabric: {units_done, adopted, ...}}
                            plus the result summary once done; the same
                            gauges flow into /metrics as
                            torrent_tpu_fabric_* while the job exists
  GET  /v1/fleet     → JSON: this process's view of the FLEET — own obs
                       digest merged with every peer's heartbeat-carried
                       digest (obs/fleet): two-level bottleneck verdict
                       (limiting process → its limiting stage), the
                       straggler scoreboard, per-process attribution.
                       A fleet-of-one from local state when no fabric
                       job runs; torrent_tpu_fleet_* series mirror it
                       on /metrics, `torrent-tpu top --fleet` renders
                       it live

Every route submits into the shared hash-plane scheduler
(``torrent_tpu/sched``) instead of owning staging buffers: pieces from
many concurrent clients coalesce into full device batches (one ~55 ms
dispatch serves everyone), per-tenant deficit round-robin keeps a greedy
client from starving a trickle one, and admission control bounds queue
memory. Clients name themselves with an ``X-Tenant`` header (default
``"default"``). When the queue is over budget a buffered request is shed
with **429** (retry later); a streaming ingest is *delayed* instead —
the blocking submit propagates backpressure to the TCP socket.

Streaming ingest (the north-star topology: a Deno client pushing a
100 GiB recheck must not need 100 GiB resident in the sidecar). The
client declares the torrent's piece length in an ``X-Piece-Length``
header and streams length-prefixed frames; the sidecar chunks them into
scheduler submissions sized to one device launch (flushed early past a
per-connection byte cap). Resident memory is bounded by the scheduler's
admission budget plus one small staging buffer per connection,
independent of body size.
Bodies may be Content-Length or chunked transfer-encoding (what a Deno
``fetch`` with a ReadableStream body produces).

  POST /v1/stream/digests   frames: u32be(len) | piece
                            → {digests: [20B, ...]}
  POST /v1/stream/verify    frames: u32be(len) | piece | 20B expected
                            → {ok: bytes, valid: int}

An ``X-Hash-Algo: sha256`` header switches the stream routes to the v2
hash plane (BEP 52 leaf/merkle hashing feeds on 32-byte digests); the
default is sha1. Digest/expected width follows the algorithm. The v2
lanes run the pallas kernel by default (``--sha256-backend`` /
``TORRENT_TPU_SHA256_BACKEND`` select pallas/scan/auto), and stream
chunking follows the lane's tile-snapped flush target so submissions
arrive launch-shaped.

Failure mapping (scheduler fault-tolerance layer, ``sched/scheduler``):
admission shed stays **429**; a launch failure that outlives retry +
bisection surfaces on the buffered routes as **503** with a
``Retry-After`` header when transient, or **500** (no Retry-After) when
deterministic — the payload itself fails the plane, so resubmitting
cannot help. Streaming responses never drop the connection for a
per-frame hash failure — failed frames come back as empty digests (or
``ok=0``) plus a ``failed`` count, so a 100 GiB recheck survives one
poisoned piece:

  {digests: [20B | "" per failed frame, ...], failed: int}
  {ok: bytes, valid: int, failed: int}   (failed ⊆ the ok=0 frames)

``--fault-plan SPEC`` (dev/test mode only — requires ``--dev`` or
``TORRENT_TPU_DEV=1``) injects deterministic faults through
``sched/faults.py`` for manual chaos runs.

Hand-rolled asyncio HTTP — no web framework needed for six routes.
"""

from __future__ import annotations

import asyncio
import json
import time

from torrent_tpu.codec.bencode import BencodeError, bdecode, bencode
from torrent_tpu.obs import (
    flight_recorder,
    histograms,
    render_obs_metrics,
    tracer,
    valid_trace_id,
)
from torrent_tpu.sched import (
    FaultPlan,
    HashPlaneScheduler,
    SchedLaunchError,
    SchedRejected,
    SchedulerConfig,
)
from torrent_tpu.utils.log import get_logger

log = get_logger("bridge")

# request-latency histogram label set stays bounded: unknown paths
# collapse into "other"
_KNOWN_ROUTES = frozenset(
    {
        "/v1/digests", "/v1/verify", "/v1/info", "/v1/trace", "/metrics",
        "/v1/pipeline", "/v1/fleet", "/v1/control",
        "/v1/timeline", "/v1/slo", "/v1/health", "/v1/swarm",
        "/v1/fabric/verify", "/v1/fabric/status",
        "/v1/stream/digests", "/v1/stream/verify",
    }
)
_H_REQUEST = (
    "torrent_tpu_bridge_request_seconds",
    "Bridge HTTP request duration by route",
)

MAX_BODY = 1 << 30  # 1 GiB of piece data per buffered (non-stream) request
# Cap on one streamed frame. 16 MiB is the practical BitTorrent piece-size
# ceiling, and it keeps the scheduler's staging-budget rule honest: the
# biggest lane bucket a client can force is 16 MiB.
MAX_PIECE = 16 << 20
# An endless frame stream must not grow the result lists without bound:
# 4M frames ≈ 80 MB of digests ≈ a 1 TiB torrent at 256 KiB pieces.
MAX_STREAM_FRAMES = 1 << 22
FRAME_TIMEOUT = 60.0  # idle seconds between frame reads before dropping
# Per-connection pre-flush staging cap: frames accumulate locally until
# handed to the scheduler, and those bytes are invisible to its admission
# budget — without this bound N streaming connections of 16 MiB pieces
# hold N × chunk × 16 MiB resident before the first enqueue.
STREAM_FLUSH_BYTES = 4 << 20


class _BodyReader:
    """Incremental body reader: Content-Length or chunked transfer-encoding.

    Exposes ``read_upto(n)`` over the framed body and ``at_eof()`` once
    the body is fully consumed — never holds more than one read's worth
    of bytes beyond the StreamReader's own buffer.
    """

    def __init__(self, reader: asyncio.StreamReader, headers: dict[bytes, bytes]):
        self._r = reader
        te = headers.get(b"transfer-encoding", b"").lower()
        self._chunked = b"chunked" in te
        try:
            self._remaining = int(headers.get(b"content-length", b"0") or 0)
        except ValueError:
            self._remaining = 0
        self._chunk_left = 0  # bytes left in the current chunk (chunked mode)
        self._done = not self._chunked and self._remaining == 0

    async def _next_chunk(self) -> None:
        size_line = await self._r.readline()
        # tolerate the CRLF terminating the previous chunk
        while size_line in (b"\r\n", b"\n"):
            size_line = await self._r.readline()
        if size_line == b"":
            # connection cut mid-body: a truncated chunked stream must NOT
            # read as clean EOF (a 200 over partial frames would be taken
            # as a completed recheck)
            raise asyncio.IncompleteReadError(b"", None)
        size = int(size_line.split(b";", 1)[0].strip(), 16)
        if size == 0:
            # trailer section until blank line
            while True:
                line = await self._r.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            self._done = True
        self._chunk_left = size

    async def read_upto(self, n: int) -> bytes:
        """Up to ``n`` body bytes; b"" at EOF."""
        if self._done:
            return b""
        if self._chunked:
            if self._chunk_left == 0:
                await self._next_chunk()
                if self._done:
                    return b""
            take = min(n, self._chunk_left)
            data = await self._r.readexactly(take)
            self._chunk_left -= take
            return data
        take = min(n, self._remaining)
        data = await self._r.readexactly(take)
        self._remaining -= take
        if self._remaining == 0:
            self._done = True
        return data

    async def at_eof(self) -> bool:
        if self._done:
            return True
        if self._chunked and self._chunk_left == 0:
            await self._next_chunk()
            return self._done
        return False


class BridgeServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        hasher: str = "tpu",
        batch_target: int = 256,
        flush_deadline_ms: float = 20.0,
        max_queue_mb: int = 256,
        tenant_max_mb: int = 128,
        fault_plan: FaultPlan | str | None = None,
        sha256_backend: str | None = None,
        autopilot=None,
        slo=None,
        timeline_interval_s: float = 1.0,
        timeline_depth: int = 512,
        slo_short_samples: int | None = None,
        slo_long_samples: int | None = None,
    ):
        self.host = host
        self.port = port
        self.hasher = hasher
        self._server: asyncio.AbstractServer | None = None
        self.sched: HashPlaneScheduler | None = None
        # scheduler autopilot (sched/control.py): True = default
        # ControlConfig, a ControlConfig instance = custom knobs,
        # None/False = no controller (bit-identical static behavior)
        self._autopilot_cfg = autopilot
        self.autopilot = None
        # timeline + SLO plane (obs/timeline, obs/slo): armed only when
        # `slo` is set (an objective spec string, a tuple of
        # SloObjective, or True for the default spec) — a run with no
        # objectives configured constructs NONE of this, so behavior is
        # bit-identical to an engine-less build
        self._slo_cfg = slo
        self._timeline_interval_s = timeline_interval_s
        self._timeline_depth = timeline_depth
        self._slo_short_samples = slo_short_samples
        self._slo_long_samples = slo_long_samples
        self.timeline = None
        self.sampler = None
        self.slo_engine = None
        # /v1/info device count, probed off-loop in the background by
        # start(): jax.devices() can block for minutes behind a wedged
        # device tunnel and must never run on the serving loop (the
        # same hazard class as sha256 backend auto-resolution)
        self._device_count = 0
        self._probe_task: asyncio.Task | None = None
        # one fabric job at a time: {"task", "executors" (the running
        # FabricExecutor appended by verify_library_fabric), "result",
        # "error", "torrents"} — /v1/fabric/* and /metrics read it
        self._fabric: dict | None = None
        # chaos harness: injected faults wrap the planes the scheduler
        # would build anyway (dev/test only — main() gates the CLI knob)
        if isinstance(fault_plan, str):
            fault_plan = FaultPlan.parse(fault_plan)
        self._sched_config = SchedulerConfig(
            batch_target=batch_target,
            flush_deadline=flush_deadline_ms / 1e3,
            max_queue_bytes=max_queue_mb << 20,
            max_tenant_bytes=tenant_max_mb << 20,
            plane_factory=(
                fault_plan.plane_factory(hasher=hasher, sha256_backend=sha256_backend)
                if fault_plan
                else None
            ),
            sha256_backend=sha256_backend,
        )

    async def start(self) -> "BridgeServer":
        self.sched = await HashPlaneScheduler(
            self._sched_config, hasher=self.hasher
        ).start()
        if self._autopilot_cfg:
            from torrent_tpu.sched.control import ControlConfig, SchedulerAutopilot

            cfg = (
                self._autopilot_cfg
                if isinstance(self._autopilot_cfg, ControlConfig)
                else ControlConfig()
            )
            self.autopilot = SchedulerAutopilot(self.sched, cfg).start()
        if self._slo_cfg:
            from torrent_tpu.obs import slo as _slo
            from torrent_tpu.obs.slo import DEFAULT_SLO_SPEC, SloEngine
            from torrent_tpu.obs.timeline import Timeline, TimelineSampler

            objectives = (
                DEFAULT_SLO_SPEC if self._slo_cfg is True else self._slo_cfg
            )
            kwargs = {}
            if self._slo_short_samples is not None:
                kwargs["short_samples"] = self._slo_short_samples
            if self._slo_long_samples is not None:
                kwargs["long_samples"] = self._slo_long_samples
            self.slo_engine = _slo.arm(SloEngine(objectives, **kwargs))
            self.timeline = Timeline(depth=self._timeline_depth)
            self.sampler = TimelineSampler(
                self.timeline,
                interval_s=self._timeline_interval_s,
                scheduler=self.sched,
                sources={
                    "control": self._control_source,
                    "fleet": self._fleet_source,
                    "distrust": self._distrust_source,
                },
                on_sample=self.slo_engine.observe,
                # bound the per-capture copy to the evaluator's window
                on_sample_tail=self.slo_engine.long_samples,
            ).start()

        def _count_devices() -> int:
            import jax

            return len(jax.devices())

        async def _probe() -> None:
            try:
                self._device_count = await asyncio.to_thread(_count_devices)
            except Exception as e:  # /v1/info keeps reporting 0
                log.warning("device-count probe failed: %s", e)

        # fire-and-forget: the probe must neither run on the serving
        # loop NOR gate the listen socket — behind a wedged tunnel every
        # other route keeps serving and /v1/info reports 0 devices until
        # the probe resolves
        self._probe_task = asyncio.ensure_future(_probe())
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("bridge listening on %s:%d", self.host, self.port)
        return self

    def close(self) -> None:
        if self._server:
            self._server.close()

    async def wait_closed(self) -> None:
        if self._server:
            await self._server.wait_closed()
        if self._probe_task is not None and not self._probe_task.done():
            # cancel releases the coroutine; an in-flight jax.devices()
            # thread finishes on its own, harmlessly
            self._probe_task.cancel()
            try:
                await self._probe_task
            except (asyncio.CancelledError, Exception):
                pass
        if self._fabric is not None and self._fabric["task"] is not None and not self._fabric["task"].done():
            self._fabric["task"].cancel()
            try:
                await self._fabric["task"]
            except (asyncio.CancelledError, Exception):
                pass
        if self.sampler is not None:
            # off-thread join + final post-mortem dump; release the
            # process-global engine slot — but only if it is still OURS
            # (a later server may have armed its own engine since)
            await asyncio.to_thread(self.sampler.stop)
            from torrent_tpu.obs import slo as _slo

            _slo.disarm(self.slo_engine)
        if self.autopilot is not None:
            await self.autopilot.close()
        if self.sched is not None:
            await self.sched.close()

    # ----------------------------------------------------- timeline sources
    # (run on the sampler THREAD; each is wrapped in a try by the
    # sampler, so a transient race with the serving loop costs one
    # sample field, never the sampler)

    def _control_source(self):
        if self.autopilot is None:
            return None
        last = self.autopilot._last or {}
        bn = (last.get("decision") or {}).get("bottleneck") or {}
        if not bn:
            return None
        return {"stage": bn.get("stage"), "confirmed": bn.get("confirmed")}

    def _fleet_source(self):
        if not (self._fabric and self._fabric["executors"]):
            return None
        bn = self._fabric["executors"][0].fleet_snapshot().get("bottleneck") or {}
        if not bn:
            return None
        return {"pid": bn.get("pid"), "stage": bn.get("stage")}

    def _distrust_source(self):
        if not (self._fabric and self._fabric["executors"]):
            return 0
        snap = self._fabric["executors"][0].metrics_snapshot()
        # every way the fabric loses trust in a verdict feeds the SLO
        # integrity objective: f = 0 sentinel rejections, Byzantine
        # audit mismatches, and receipt convictions
        return (
            snap.get("sentinel_mismatches", 0)
            + snap.get("audit_mismatches", 0)
            + snap.get("convictions", 0)
        )

    # ----------------------------------------------------------- streaming

    @staticmethod
    def _tenant_of(headers) -> str:
        return (headers or {}).get(b"x-tenant", b"default").decode("latin-1")[:64]

    async def _route_stream(self, writer, target: str, headers, body: _BodyReader):
        """Length-prefixed frame ingest through the scheduler.

        Frames are chunked into scheduler submissions sized to one device
        launch; the queue's admission budget bounds resident memory while
        launches overlap further ingest. A full queue *delays* the read
        loop (blocking submit) — backpressure reaches the client's TCP
        socket instead of buffering without bound.
        """
        mode = target.rsplit("/", 1)[-1]
        if mode not in ("digests", "verify"):
            return await self._reply(writer, 404, b"not found")
        try:
            plen = int(headers.get(b"x-piece-length", b"0") or 0)
        except ValueError:
            plen = 0
        if plen <= 0 or plen > MAX_PIECE:
            return await self._reply(writer, 400, b"X-Piece-Length required (1..16MiB)")
        algo = headers.get(b"x-hash-algo", b"sha1").decode("latin-1").lower()
        if algo not in ("sha1", "sha256"):
            return await self._reply(writer, 400, b"X-Hash-Algo must be sha1 or sha256")
        await self._stream_sched(writer, mode, plen, body, algo, self._tenant_of(headers))

    @staticmethod
    async def _read_idle_bounded(body: _BodyReader, n: int) -> bytes:
        """``readexactly(n)`` where the timeout bounds *idle* time, not
        total transfer time — each successful chunk resets the clock, so a
        slow-but-live client streaming a big piece is never dropped."""
        parts, got = [], 0
        while got < n:
            chunk = await asyncio.wait_for(
                body.read_upto(min(n - got, 1 << 18)), FRAME_TIMEOUT
            )
            if not chunk:
                raise asyncio.IncompleteReadError(b"".join(parts), n)
            parts.append(chunk)
            got += len(chunk)
        return b"".join(parts)

    async def _read_frame(
        self, body: _BodyReader, plen: int, with_expected: bool, digest_len: int = 20
    ):
        """One ``len | piece [| expected]`` frame, or None at clean EOF.

        Reads are idle-bounded so a silent client can't pin queue bytes
        forever. Raises ValueError on an oversized frame.
        """
        if await asyncio.wait_for(body.at_eof(), FRAME_TIMEOUT):
            return None
        ln = int.from_bytes(await self._read_idle_bounded(body, 4), "big")
        if ln > plen:
            raise ValueError("frame exceeds X-Piece-Length")
        data = await self._read_idle_bounded(body, ln)
        expected = (
            await self._read_idle_bounded(body, digest_len) if with_expected else None
        )
        return data, expected

    async def _stream_sched(
        self, writer, mode: str, plen: int, body: _BodyReader, algo: str, tenant: str
    ):
        dlen = 32 if algo == "sha256" else 20
        # plane-aware chunking: pallas sha256 lanes have tile-snapped
        # flush targets, so stream submissions arrive launch-shaped
        chunk = self.sched.chunk_for(plen, algo)
        futs: list[tuple[asyncio.Future, int]] = []
        batch: list[bytes] = []
        batch_exp: list[bytes] = []
        batch_bytes = 0
        n_frames = 0

        async def flush():
            nonlocal batch, batch_exp, batch_bytes
            fut = await self.sched.enqueue(
                tenant,
                batch,
                expected=batch_exp if mode == "verify" else None,
                algo=algo,
                piece_length=plen,
                wait=True,  # streaming backpressure, not load-shed
            )
            futs.append((fut, len(batch)))
            batch, batch_exp, batch_bytes = [], [], 0

        try:
            while True:
                frame = await self._read_frame(body, plen, mode == "verify", digest_len=dlen)
                if frame is None:
                    break
                n_frames += 1
                if n_frames > MAX_STREAM_FRAMES:
                    return await self._reply(writer, 413, b"too many frames")
                data, exp = frame
                batch.append(data)
                batch_bytes += len(data)
                if exp is not None:
                    batch_exp.append(exp)
                # flush on byte budget too, not just piece count: the
                # pre-flush batch is per-CONNECTION memory the admission
                # budget can't see, so big-piece streams must hand bytes
                # to the scheduler (where wait=True bounds them) early —
                # N connections otherwise hold N × chunk × plen resident
                if len(batch) >= chunk or batch_bytes >= STREAM_FLUSH_BYTES:
                    await flush()
            if batch:
                await flush()
            digests: list[bytes] = []
            ok_flags = bytearray()
            failed = 0
            for fut, npieces in futs:
                # a per-frame hash failure (retry/bisection exhausted)
                # must not drop the whole connection: report the frames
                # as failed and keep streaming the rest of the response
                try:
                    res = await fut
                except SchedLaunchError as e:
                    log.warning("stream frames failed (%d pieces): %s", npieces, e)
                    failed += npieces
                    if mode == "digests":
                        digests.extend([b""] * npieces)
                    else:
                        ok_flags.extend(b"\x00" * npieces)
                    continue
                if mode == "digests":
                    digests.extend(res)
                else:
                    ok_flags.extend(res)
            if mode == "digests":
                payload = bencode({b"digests": digests, b"failed": failed})
            else:
                payload = bencode(
                    {b"ok": bytes(ok_flags), b"valid": sum(ok_flags), b"failed": failed}
                )
            await self._reply(writer, 200, payload)
        except ValueError as e:
            await self._reply(writer, 400, str(e).encode())
        except SchedRejected as e:
            await self._reply(writer, 429, str(e).encode())

    # --------------------------------------------------------------- http

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = (await asyncio.wait_for(reader.readline(), 60)).split()
            if len(request_line) < 2:
                return await self._reply(writer, 400, b"bad request")
            method, target = request_line[0].decode(), request_line[1].decode()
            headers: dict[bytes, bytes] = {}
            header_bytes = 0
            while True:
                line = await asyncio.wait_for(reader.readline(), 60)
                if line in (b"\r\n", b"\n", b""):
                    break
                header_bytes += len(line)
                if header_bytes > (16 << 10):  # endless header lines ≠ a request
                    return await self._reply(writer, 431, b"headers too large")
                if b":" in line:
                    k, v = line.split(b":", 1)
                    headers[k.strip().lower()] = v.strip()
            # trace ids are minted HERE (or honored from X-Trace-Id when
            # it is a well-formed token): every request runs inside a
            # root span, the scheduler threads it through the ticket
            # lifecycle, and _reply echoes it so the client can fetch
            # the span tree from GET /v1/trace?id=…
            raw_tid = headers.get(b"x-trace-id", b"").decode("latin-1").strip()
            trace_id = raw_tid if valid_trace_id(raw_tid) else tracer().mint()
            path = target.split("?")[0]
            route = path if path in _KNOWN_ROUTES else "other"
            t0 = time.monotonic()
            try:
                with tracer().span(
                    "bridge.request", trace_id=trace_id, method=method,
                    target=path, tenant=self._tenant_of(headers),
                ):
                    if method == "POST" and target.startswith("/v1/stream/"):
                        body_reader = _BodyReader(reader, headers)
                        return await self._route_stream(
                            writer, target, headers, body_reader
                        )
                    try:
                        content_length = int(headers.get(b"content-length", b"0") or 0)
                    except ValueError:
                        return await self._reply(writer, 400, b"bad content-length")
                    if content_length > MAX_BODY:
                        return await self._reply(writer, 413, b"body too large")
                    body = (
                        await reader.readexactly(content_length)
                        if content_length
                        else b""
                    )
                    await self._route(writer, method, target, body, headers)
            finally:
                histograms().get(*_H_REQUEST, route=route).observe(
                    time.monotonic() - t0
                )
        except (asyncio.TimeoutError, asyncio.IncompleteReadError, ConnectionError, OSError):
            writer.close()
        except Exception as e:  # one bad request must not kill the sidecar
            log.error("bridge error: %s", e)
            await self._reply(writer, 500, str(e).encode())

    async def _route(self, writer, method: str, target: str, body: bytes, headers=None):
        if method == "GET" and target == "/v1/info":
            payload = bencode(
                {
                    b"backend": self.hasher.encode(),
                    # probed off-loop in start() — never on the serving loop
                    b"devices": self._device_count,
                    b"batch": self.sched.config.batch_target,
                    # memoized on the scheduler (start() resolved it
                    # off-loop; 'auto' probes jax.devices())
                    b"sha256_backend": (
                        b"cpu"
                        if self.hasher == "cpu"
                        else self.sched.sha256_backend().encode()
                    ),
                    b"version": b"torrent-tpu/0.1",
                }
            )
            return await self._reply(writer, 200, payload)
        if method == "GET" and target.split("?")[0] == "/metrics":
            from torrent_tpu.utils.metrics import (
                render_fabric_metrics,
                render_sched_metrics,
            )

            text = render_sched_metrics(self.sched)
            if self._fabric and self._fabric["executors"]:
                from torrent_tpu.utils.metrics import render_fleet_metrics

                ex = self._fabric["executors"][0]
                text += render_fabric_metrics(ex.metrics_snapshot())
                # the swarm-wide view: this process's fleet rollup from
                # its own + heartbeat-carried peer digests
                text += render_fleet_metrics(ex.fleet_snapshot())
            if self.autopilot is not None:
                from torrent_tpu.utils.metrics import render_control_metrics

                text += render_control_metrics(self.autopilot.metrics_snapshot())
            if self.timeline is not None:
                from torrent_tpu.utils.metrics import (
                    render_slo_metrics,
                    render_timeline_metrics,
                )

                # stats(), not snapshot(): a scrape must not copy the
                # whole ring just to report its counters
                tl = self.timeline.stats()
                tl["sampler_alive"] = (
                    self.sampler.alive if self.sampler is not None else False
                )
                text += render_timeline_metrics(tl)
                text += render_slo_metrics(
                    self.slo_engine.report() if self.slo_engine else None
                )
            text += render_obs_metrics()
            from torrent_tpu.analysis import sanitizer

            if sanitizer.is_enabled():
                from torrent_tpu.utils.metrics import render_tsan_metrics

                text += render_tsan_metrics(sanitizer.snapshot())
            # the Prometheus exposition format has its own content type;
            # collectors (and promtool) reject octet-stream
            return await self._reply(
                writer, 200, text.encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        if method == "GET" and target.split("?")[0] == "/v1/trace":
            return await self._trace_route(writer, target)
        if method == "GET" and target.split("?")[0] == "/v1/pipeline":
            return await self._pipeline_route(writer)
        if method == "GET" and target.split("?")[0] == "/v1/fleet":
            return await self._fleet_route(writer)
        if method == "GET" and target.split("?")[0] == "/v1/control":
            return await self._control_route(writer)
        if method == "GET" and target.split("?")[0] == "/v1/timeline":
            return await self._timeline_route(writer)
        if method == "GET" and target.split("?")[0] == "/v1/slo":
            return await self._slo_route(writer)
        if method == "GET" and target.split("?")[0] == "/v1/health":
            return await self._health_route(writer)
        if method == "GET" and target.split("?")[0] == "/v1/swarm":
            return await self._swarm_route(writer)
        if method == "GET" and target == "/v1/fabric/status":
            return await self._reply(writer, 200, bencode(self._fabric_status()))
        if method != "POST":
            return await self._reply(writer, 405, b"method not allowed")
        if target == "/v1/fabric/verify":
            return await self._fabric_verify(writer, body)
        # the buffered hash routes are sha1-only; a sha256 request must
        # fail closed, not silently return v1 digests with a 200 (the
        # algorithm-agnostic /v1/info above is exempt)
        algo = (headers or {}).get(b"x-hash-algo", b"sha1").decode("latin-1").lower()
        if algo != "sha1":
            return await self._reply(
                writer, 400, b"buffered routes are sha1-only; use /v1/stream/* for sha256"
            )
        try:
            req = bdecode(body)
        except BencodeError as e:
            return await self._reply(writer, 400, f"bad bencode: {e}".encode())
        if not isinstance(req, dict) or not isinstance(req.get(b"pieces"), list):
            return await self._reply(writer, 400, b"missing pieces list")
        pieces = req[b"pieces"]
        if not all(isinstance(p, bytes) for p in pieces):
            return await self._reply(writer, 400, b"pieces must be bytestrings")
        if any(len(p) > MAX_PIECE for p in pieces):
            # same cap as the stream routes: an oversized piece would open
            # (and cache) a scheduler lane far beyond the staging budget
            return await self._reply(writer, 413, b"piece exceeds 16MiB cap")
        tenant = self._tenant_of(headers)

        if target == "/v1/digests":
            try:
                digests = await self.sched.submit(tenant, pieces, algo="sha1")
            except SchedRejected as e:
                return await self._reply(writer, 429, str(e).encode())
            except SchedLaunchError as e:
                return await self._reply_launch_failed(writer, e)
            return await self._reply(writer, 200, bencode({b"digests": digests}))
        if target == "/v1/verify":
            expected = req.get(b"expected")
            if (
                not isinstance(expected, list)
                or len(expected) != len(pieces)
                or not all(isinstance(e, bytes) and len(e) == 20 for e in expected)
            ):
                return await self._reply(writer, 400, b"expected must be 20-byte hashes")
            try:
                ok = await self.sched.submit(
                    tenant, pieces, expected=expected, algo="sha1"
                )
            except SchedRejected as e:
                return await self._reply(writer, 429, str(e).encode())
            except SchedLaunchError as e:
                return await self._reply_launch_failed(writer, e)
            return await self._reply(writer, 200, bencode({b"ok": ok}))
        await self._reply(writer, 404, b"not found")

    # ------------------------------------------------------------- fabric

    async def _fabric_verify(self, writer, body: bytes):
        """Start a scheduler-fed library recheck of local torrents.

        Body (bencode): ``{items: [{torrent: PATH, root: PATH}, ...],
        unit_mb?: int}`` — paths are local to the sidecar host, the same
        trust model as the CLI (the bridge binds loopback by default).
        Replies 202 immediately; poll ``GET /v1/fabric/status``. One job
        at a time: a second POST while one runs gets 409.
        """
        from torrent_tpu.codec.metainfo import parse_metainfo
        from torrent_tpu.storage.storage import FsStorage, Storage

        if self._fabric is not None and (
            self._fabric["task"] is None or not self._fabric["task"].done()
        ):
            return await self._reply(writer, 409, b"fabric verify already running")
        try:
            req = bdecode(body)
        except BencodeError as e:
            return await self._reply(writer, 400, f"bad bencode: {e}".encode())
        specs = req.get(b"items") if isinstance(req, dict) else None
        if not isinstance(specs, list) or not specs:
            return await self._reply(writer, 400, b"missing items list")
        for spec in specs:
            if not isinstance(spec, dict) or not isinstance(
                spec.get(b"torrent"), bytes
            ):
                return await self._reply(
                    writer, 400, b"each item needs torrent and root paths"
                )

        # claim the job slot BEFORE the first await: a concurrent POST
        # suspended in load_items must hit the 409 above, not race two
        # sweeps into one record (task=None means "starting" = busy)
        job = self._fabric = {
            "executors": [],
            "result": None,
            "error": None,
            "torrents": len(specs),
            "task": None,
        }

        def load_items():
            # disk reads + parses off the event loop: a long manifest on
            # slow storage must not stall concurrent hash requests
            out = []
            for spec in specs:
                tpath = spec[b"torrent"].decode("utf-8", "surrogateescape")
                root = spec.get(b"root", b".").decode("utf-8", "surrogateescape")
                try:
                    with open(tpath, "rb") as f:
                        meta = parse_metainfo(f.read())
                except OSError as e:
                    raise ValueError(f"cannot read {tpath}: {e}") from e
                if meta is None:
                    raise ValueError(f"not a v1 .torrent: {tpath}")
                out.append((Storage(FsStorage(root), meta.info), meta.info))
            return out

        try:
            items = await asyncio.to_thread(load_items)
        except ValueError as e:
            self._fabric = None  # release the claim: nothing ran
            return await self._reply(writer, 400, str(e).encode())
        unit_mb = req.get(b"unit_mb")
        unit_bytes = (unit_mb << 20) if isinstance(unit_mb, int) and unit_mb > 0 else None
        job["task"] = asyncio.ensure_future(
            self._run_fabric(job, items, unit_bytes)
        )
        total = sum(info.num_pieces for _, info in items)
        return await self._reply(
            writer,
            202,
            bencode({b"state": b"started", b"torrents": len(items), b"pieces": total}),
        )

    async def _run_fabric(self, job: dict, items, unit_bytes) -> None:
        from torrent_tpu.parallel.bulk import verify_library_fabric

        try:
            res = await verify_library_fabric(
                items,
                self.sched,
                unit_bytes=unit_bytes,
                executor_out=job["executors"],
            )
        except Exception as e:  # surfaced via /v1/fabric/status
            log.error("fabric verify failed: %s", e)
            job["error"] = str(e)
            return
        job["result"] = {
            b"valid": sum(int(bf.sum()) for bf in res.bitfields),
            b"pieces": res.n_pieces,
            b"per_torrent": [int(bf.sum()) for bf in res.bitfields],
            b"millis": int(res.seconds * 1000),
        }

    def _fabric_status(self) -> dict:
        job = self._fabric
        if job is None:
            return {b"state": b"idle"}
        out: dict = {b"torrents": job["torrents"]}
        if job["error"] is not None:
            out[b"state"] = b"failed"
            out[b"error"] = job["error"].encode()
        elif job["result"] is not None:
            out[b"state"] = b"done"
            out[b"result"] = job["result"]
        else:
            out[b"state"] = b"running"
        if job["executors"]:
            s = job["executors"][0].metrics_snapshot()
            out[b"fabric"] = {
                b"pid": s["pid"],
                b"nproc": s["nproc"],
                b"plan": s["plan_fingerprint"].encode(),
                b"shard_units": s["shard_units"],
                b"shard_bytes": s["shard_bytes"],
                b"units_done": s["units_done"],
                b"units_adopted": s["units_adopted"],
                b"pieces_verified": s["pieces_verified"],
                b"sentinel_checks": s["sentinel_checks"],
                b"sentinel_mismatches": s["sentinel_mismatches"],
                b"byzantine_f": s.get("byzantine_f", 0),
                b"quorum_need": s.get("quorum_need", 1),
                b"audit_checks": s.get("audit_checks", 0),
                b"audit_mismatches": s.get("audit_mismatches", 0),
                b"convictions": s.get("convictions", 0),
                b"stragglers": s["stragglers"],
                b"heartbeat_age_ms": int(s["heartbeat_age"] * 1000),
                b"degraded": int(s["degraded"]),
            }
        return out

    async def _pipeline_route(self, writer):
        """``GET /v1/pipeline`` — the bottleneck attribution surface.

        Returns the pipeline ledger's since-start per-stage snapshot,
        the attributor's verdict (limiting stage, achieved vs demanded
        rate), and a small scheduler summary so ``torrent-tpu top`` can
        render queue depth next to stage utilization. JSON with sorted
        keys, same operator-surface conventions as ``/v1/trace``; pure
        in-memory reads, safe on the serving loop."""
        from torrent_tpu.obs.attrib import attribute
        from torrent_tpu.obs.ledger import pipeline_ledger

        snap = pipeline_ledger().snapshot()
        sched_snap = self.sched.metrics_snapshot() if self.sched else {}
        body = json.dumps(
            {
                "attribution": attribute(snap),
                "snapshot": snap,
                # autopilot view for `torrent-tpu top`'s decision line
                # (null when no controller is attached)
                "control": (
                    self.autopilot.status() if self.autopilot is not None else None
                ),
                "sched": {
                    "queue_pieces": sched_snap.get("queue_pieces", 0),
                    "queue_bytes": sched_snap.get("queue_bytes", 0),
                    "launches": sched_snap.get("launches", 0),
                    "mean_fill": sched_snap.get("mean_fill", 0.0),
                    "lanes": sched_snap.get("lanes", 0),
                    "cpu_fallback_launches": sched_snap.get(
                        "cpu_fallback_launches", 0
                    ),
                },
            },
            sort_keys=True,
        ).encode()
        return await self._reply(
            writer, 200, body, content_type="application/json"
        )

    async def _fleet_route(self, writer):
        """``GET /v1/fleet`` — this process's view of the fleet.

        While a fabric job runs (or after it finished) the rollup comes
        from the executor: own obs digest + every peer's heartbeat-
        carried digest, two-level bottleneck attribution, straggler
        scoreboard. With no fabric job it degrades to a fleet-of-one
        built from local obs state, so the route (and ``top --fleet``)
        always answers. JSON with sorted keys; pure in-memory reads,
        safe on the serving loop."""
        from torrent_tpu.obs.fleet import local_fleet_snapshot

        if self._fabric and self._fabric["executors"]:
            roll = self._fabric["executors"][0].fleet_snapshot()
        else:
            roll = local_fleet_snapshot(self.sched)
        body = json.dumps(roll, sort_keys=True).encode()
        return await self._reply(
            writer, 200, body, content_type="application/json"
        )

    async def _control_route(self, writer):
        """``GET /v1/control`` — the scheduler autopilot's surface.

        Last decision (bottleneck verdict + actions), the applied
        actuator moves, the inputs the decision saw, and every
        actuator's current value. Always answers: with no autopilot
        attached it reports ``attached: false`` so operators can tell
        "controller off" from "bridge down". JSON with sorted keys;
        pure in-memory reads, safe on the serving loop."""
        if self.autopilot is None:
            payload: dict = {"attached": False, "enabled": False, "decision": None}
        else:
            payload = {"attached": True, **self.autopilot.status()}
        body = json.dumps(payload, sort_keys=True).encode()
        return await self._reply(
            writer, 200, body, content_type="application/json"
        )

    async def _timeline_route(self, writer):
        """``GET /v1/timeline`` — the obs plane's history surface.

        The bounded sample ring (attached: false when no timeline is
        armed), dumpable/replayable via ``torrent-tpu replay``. JSON
        with sorted keys; pure in-memory reads, safe on the serving
        loop."""
        if self.timeline is None:
            payload: dict = {"attached": False, "samples": [], "drops": 0}
        else:
            payload = {"attached": True, **self.timeline.snapshot()}
            payload["sampler_alive"] = (
                self.sampler.alive if self.sampler is not None else False
            )
        body = json.dumps(payload, sort_keys=True).encode()
        return await self._reply(
            writer, 200, body, content_type="application/json"
        )

    async def _slo_route(self, writer):
        """``GET /v1/slo`` — declared objectives, burn rates, budget.

        The engine's last evaluation report (attached: false when no
        objectives are configured — operators can tell "SLO off" from
        "bridge down"). JSON with sorted keys; pure in-memory reads."""
        if self.slo_engine is None:
            payload: dict = {"attached": False, "report": None}
        else:
            payload = {
                "attached": True,
                "objectives": [
                    {"name": o.name, "kind": o.kind, "target": o.target,
                     "family": o.family}
                    for o in self.slo_engine.objectives
                ],
                "report": self.slo_engine.report(),
                "breach_dumps": self.slo_engine.metrics_snapshot()[
                    "breach_dumps"
                ],
            }
        body = json.dumps(payload, sort_keys=True).encode()
        return await self._reply(
            writer, 200, body, content_type="application/json"
        )

    async def _health_route(self, writer):
        """``GET /v1/health`` — liveness + readiness for a real load
        balancer. Always answers (liveness IS the reply); HTTP 200 only
        when READY — the backend probe resolved, no lane breaker stuck
        open past its cooldown, the sampler (when armed) alive, and no
        SLO objective in breach (breach = ``degraded``: live, but
        leave the rotation while the budget burns)."""
        from torrent_tpu.obs.slo import build_health

        probe_ok = self._probe_task is None or self._probe_task.done()
        breakers = (
            self.sched.metrics_snapshot().get("breakers", {})
            if self.sched is not None
            else {}
        )
        health = build_health(
            probe_ok=probe_ok,
            breakers=breakers,
            sampler_alive=(
                self.sampler.alive if self.sampler is not None else None
            ),
            slo_report=(
                self.slo_engine.report() if self.slo_engine is not None else None
            ),
        )
        body = json.dumps(health, sort_keys=True).encode()
        return await self._reply(
            writer, 200 if health["ready"] else 503, body,
            content_type="application/json",
        )

    async def _swarm_route(self, writer):
        """``GET /v1/swarm`` — the swarm wire plane's telemetry surface.

        The process-global :mod:`obs/swarm` registry's bounded snapshot:
        top-K peers + overflow fold, choke timelines, block-RTT
        summaries, announce health, flight-trigger counters. Always
        answers (an idle hash-plane sidecar reports zero peers). JSON
        with sorted keys; pure in-memory reads, safe on the serving
        loop."""
        from torrent_tpu.obs.swarm import swarm_telemetry
        from torrent_tpu.serve_plane.telemetry import serve_telemetry

        payload = swarm_telemetry().snapshot()
        serve_obs = serve_telemetry()
        if serve_obs.active():
            # serving-side entries ride along once this process has
            # actually served (same additive rule as /metrics)
            payload["serve"] = serve_obs.snapshot()
        body = json.dumps(payload, sort_keys=True).encode()
        return await self._reply(
            writer, 200, body, content_type="application/json"
        )

    async def _trace_route(self, writer, target: str):
        """``GET /v1/trace`` — the obs plane's query surface.

        ``?id=<trace>`` returns that trace's ordered span tree (the
        ticket lifecycle a client tagged with ``X-Trace-Id``); without
        an id it returns the flight recorder's black-box dumps plus the
        known trace ids. JSON (sorted keys), not bencode: this is an
        operator/debugging surface, not a data-plane wire format.
        """
        params: dict[str, str] = {}
        for part in target.partition("?")[2].split("&"):
            if "=" in part:
                k, _, v = part.partition("=")
                params[k] = v
        tid = params.get("id")
        if tid:
            tree = tracer().trace_tree(tid)
            if tree is None:
                return await self._reply(
                    writer, 404, b'{"error": "unknown trace id"}',
                    content_type="application/json",
                )
            body = json.dumps(tree, sort_keys=True).encode()
        else:
            rec = flight_recorder()
            body = json.dumps(
                {
                    "dump_counts": rec.counts(),
                    "dumps": rec.dumps(),
                    "traces": tracer().trace_ids(),
                },
                sort_keys=True,
            ).encode()
        return await self._reply(
            writer, 200, body, content_type="application/json"
        )

    async def _reply_launch_failed(self, writer, e: SchedLaunchError):
        # transient retry-exhausted failure: 503 + Retry-After (shed is
        # 429 — different remedy). A deterministic (payload-caused)
        # failure must NOT advertise Retry-After: resubmitting the same
        # payload re-runs the whole retry+bisection cascade forever — 500
        # tells the client the request itself is the problem.
        if e.kind == "transient":
            return await self._reply(
                writer, 503, str(e).encode(), headers={"Retry-After": "1"}
            )
        return await self._reply(writer, 500, str(e).encode())

    async def _reply(
        self,
        writer,
        status: int,
        body: bytes,
        headers=None,
        content_type: str = "application/octet-stream",
    ):
        try:
            head = (
                f"HTTP/1.1 {status} X\r\nContent-Type: {content_type}\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n"
            )
            # every traced request echoes its trace id, honored or
            # minted, so the client can fetch GET /v1/trace?id=…
            ctx = tracer().current_context()
            if ctx is not None:
                head += f"X-Trace-Id: {ctx[0]}\r\n"
            for k, v in (headers or {}).items():
                head += f"{k}: {v}\r\n"
            head += "\r\n"
            writer.write(head.encode("latin-1") + body)
            await writer.drain()
        except (ConnectionError, OSError):
            pass
        finally:
            writer.close()


async def serve_bridge(
    host: str = "127.0.0.1", port: int = 8421, hasher: str = "tpu", **sched_kwargs
) -> BridgeServer:
    return await BridgeServer(host, port, hasher, **sched_kwargs).start()


def main(argv=None):  # pragma: no cover - manual entrypoint
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8421)
    parser.add_argument("--hasher", choices=("cpu", "tpu"), default="tpu")
    parser.add_argument(
        "--batch-target", type=int, default=256,
        help="pieces per device launch the scheduler aims to fill",
    )
    parser.add_argument(
        "--flush-deadline-ms", type=float, default=20.0,
        help="max ms a lone queued piece waits before a partial flush",
    )
    parser.add_argument(
        "--max-queue-mb", type=int, default=256,
        help="global admission bound on queued piece bytes (429 beyond)",
    )
    parser.add_argument(
        "--tenant-max-mb", type=int, default=128,
        help="per-tenant admission bound on queued piece bytes",
    )
    parser.add_argument(
        "--sha256-backend", choices=("auto", "pallas", "scan"), default=None,
        help="v2 (sha256) device plane: hand-tiled pallas kernel, lax.scan "
        "fallback, or auto (pallas on TPU-kind devices). Defaults to the "
        "TORRENT_TPU_SHA256_BACKEND env, then auto",
    )
    parser.add_argument(
        "--autopilot", action="store_true",
        help="arm the scheduler autopilot (sched/control.py): adaptive "
        "lane batch targets/flush deadlines, admission budgets that "
        "follow the limiting stage, and hysteresis-guarded backend "
        "steering, driven by the pipeline ledger's attribution. "
        "GET /v1/control serves the decisions either way",
    )
    parser.add_argument(
        "--autopilot-interval", type=float, default=1.0, metavar="S",
        help="seconds between controller decisions (default %(default)s)",
    )
    parser.add_argument(
        "--slo", nargs="?", const=True, default=None, metavar="SPEC",
        help="arm the timeline sampler + SLO engine (obs/timeline, "
        "obs/slo): declarative objectives evaluated over a bounded "
        "sample ring, e.g. 'availability=0.999;p99_ms=50:queue_wait;"
        "floor_mibps=10;integrity=on' (no SPEC = the default "
        "availability+integrity contract). Serves GET /v1/timeline, "
        "/v1/slo and torrent_tpu_slo_*//timeline_* metrics; "
        "/v1/health reflects breaches either way",
    )
    parser.add_argument(
        "--timeline-interval", type=float, default=1.0, metavar="S",
        help="seconds between timeline samples when --slo is armed "
        "(default %(default)s)",
    )
    parser.add_argument(
        "--fault-plan", default=None, metavar="SPEC",
        help="inject deterministic hash-plane faults (sched/faults.py spec, "
        "e.g. 'fail_first=3;latency_ms=5'); dev/test mode only",
    )
    parser.add_argument(
        "--dev", action="store_true",
        help="dev/test mode: unlocks chaos knobs like --fault-plan",
    )
    args = parser.parse_args(argv)

    fault_plan = None
    if args.fault_plan:
        # chaos knobs must not leak into production invocations: require
        # an explicit dev-mode opt-in (flag or env), and fail closed
        import os
        import sys

        if not (args.dev or os.environ.get("TORRENT_TPU_DEV", "") in ("1", "true")):
            print(
                "error: --fault-plan is a dev/test chaos knob; pass --dev "
                "or set TORRENT_TPU_DEV=1 to use it",
                file=sys.stderr,
            )
            return 2
        try:
            fault_plan = FaultPlan.parse(args.fault_plan)
        except ValueError as e:
            print(f"error: bad --fault-plan: {e}", file=sys.stderr)
            return 2

    autopilot = None
    if args.autopilot:
        from torrent_tpu.sched.control import ControlConfig

        autopilot = ControlConfig(interval_s=args.autopilot_interval)

    async def go():
        server = await serve_bridge(
            args.host,
            args.port,
            args.hasher,
            batch_target=args.batch_target,
            flush_deadline_ms=args.flush_deadline_ms,
            max_queue_mb=args.max_queue_mb,
            tenant_max_mb=args.tenant_max_mb,
            fault_plan=fault_plan,
            sha256_backend=args.sha256_backend,
            autopilot=autopilot,
            slo=args.slo,
            timeline_interval_s=args.timeline_interval,
        )
        print(f"bridge listening on {args.host}:{server.port}")
        await server.wait_closed()

    asyncio.run(go())
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
