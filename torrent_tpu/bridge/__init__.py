from torrent_tpu.bridge.service import BridgeServer, serve_bridge

__all__ = ["BridgeServer", "serve_bridge"]
