"""BEP 19 webseeds (HTTP seeding) — beyond the reference's surface.

A web server holding the torrent's payload acts as an always-available
seed: pieces are fetched with HTTP Range requests and enter the torrent
through the same verify→persist→have path as wire pieces, so a corrupt
or lying webseed is caught by SHA1 exactly like a poisoning peer.

URL mapping (BEP 19): a ``url-list`` entry ending in ``/`` is a base —
append ``name`` (single-file) or ``name/…path`` (multi-file, each
component %-escaped); otherwise the URL is used as-is for single-file
torrents. Multi-file pieces that span file boundaries issue one ranged
GET per file segment.
"""

from __future__ import annotations

import http.client
import urllib.error
import urllib.parse
import urllib.request

from torrent_tpu.codec.metainfo import InfoDict
from torrent_tpu.storage.storage import Storage
from torrent_tpu.utils.log import get_logger

log = get_logger("session.webseed")

FETCH_TIMEOUT = 30.0


class WebSeedError(Exception):
    pass


def allowed_url(url: str) -> bool:
    """True for http/https webseed URLs. Both url-list fields (torrent
    files) and ws= params (magnets) are UNTRUSTED input, and fetch_range
    feeds the URL to urllib — which happily opens file:// and ftp://.
    Anything but plain web schemes is refused before a loop ever spawns
    (SSRF / local-file-read guard)."""
    try:
        return urllib.parse.urlsplit(url).scheme in ("http", "https")
    except ValueError:
        return False


def url_for(base: str, info: InfoDict, path: tuple[str, ...]) -> str:
    """Resolve the GET URL for one file of the torrent (BEP 19 §url-list)."""
    if base.endswith("/"):
        parts = [urllib.parse.quote(c) for c in path]
        return base + "/".join(parts)
    if info.is_multi_file:
        # non-slash base with multi-file still appends per convention
        parts = [urllib.parse.quote(c) for c in path]
        return base + "/" + "/".join(parts)
    return base


def fetch_range(url: str, start: int, length: int) -> bytes:
    """One ranged GET; raises WebSeedError on anything but full success."""
    req = urllib.request.Request(
        url,
        headers={
            "Range": f"bytes={start}-{start + length - 1}",
            "User-Agent": "torrent-tpu/0.1",
        },
    )
    try:
        with urllib.request.urlopen(req, timeout=FETCH_TIMEOUT) as resp:
            if resp.status not in (200, 206):
                raise WebSeedError(f"{url}: HTTP {resp.status}")
            data = resp.read(length + 1)
    except (urllib.error.URLError, http.client.HTTPException, OSError, TimeoutError) as e:
        raise WebSeedError(f"{url}: {e}") from e
    if resp.status == 200:
        # server ignored the Range header; BEP 19 servers shouldn't, and
        # re-downloading the whole file per piece would be pathological
        raise WebSeedError(f"{url}: server ignored Range request")
    if len(data) != length:
        raise WebSeedError(f"{url}: short range read {len(data)}/{length}")
    return data


def fetch_piece(base: str, storage: Storage, info: InfoDict, index: int) -> bytes:
    """Assemble one piece from ranged GETs (per spanned file segment)."""
    from torrent_tpu.storage.piece import piece_length

    plen = piece_length(info, index)
    out = bytearray()
    for path, foff, chunk in storage.segments(index * info.piece_length, plen):
        if path is None:
            out += bytes(chunk)  # BEP 47 pad span: zeros, nothing to fetch
            continue
        out += fetch_range(url_for(base, info, path), foff, chunk)
    return bytes(out)


def fetch_piece_bep17(url: str, info_hash: bytes, info: InfoDict, index: int) -> bytes:
    """BEP 17 httpseed GET: ``{url}?info_hash=<%-escaped>&piece=N``.

    The Hoffman protocol serves whole pieces keyed by infohash rather
    than file byte ranges (BEP 19); the response body IS the piece."""
    from torrent_tpu.storage.piece import piece_length

    sep = "&" if urllib.parse.urlsplit(url).query else "?"
    get = (
        f"{url}{sep}info_hash={urllib.parse.quote_from_bytes(info_hash)}"
        f"&piece={index}"
    )
    plen = piece_length(info, index)
    req = urllib.request.Request(get, headers={"User-Agent": "torrent-tpu/0.1"})
    try:
        with urllib.request.urlopen(req, timeout=FETCH_TIMEOUT) as resp:
            if resp.status != 200:
                raise WebSeedError(f"{url}: HTTP {resp.status}")
            data = resp.read(plen + 1)
    except (urllib.error.URLError, http.client.HTTPException, OSError, TimeoutError) as e:
        raise WebSeedError(f"{url}: {e}") from e
    if len(data) != plen:
        raise WebSeedError(f"{url}: piece {index} wrong size {len(data)}/{plen}")
    return data
