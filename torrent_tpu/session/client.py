"""Client: TCP listener, torrent registry, accept loop (ref L6: client.ts).

Owns the listening socket and peer identity, routes inbound handshakes to
torrents by info hash *before* replying so unknown torrents are dropped
silently (client.ts:85-104), and shares one TPUVerifier across torrents
when the 'tpu' hasher is selected.

Fixed vs the reference: config defaults are copied per-instance instead
of mutating a shared defaults object (client.ts:47, SURVEY §8.2), and the
broken ``fileStorage`` import (§8.1) has no analogue — storage backends
are injected explicitly.
"""

from __future__ import annotations

import asyncio
import random
import string
import dataclasses
from dataclasses import dataclass, field

from torrent_tpu.codec.metainfo import Metainfo
from torrent_tpu.net import protocol as proto
from torrent_tpu.session.torrent import Torrent, TorrentConfig
from torrent_tpu.storage.storage import FsStorage, Storage, StorageMethod
from torrent_tpu.utils.log import get_logger

log = get_logger("session.client")

PEER_ID_PREFIX = b"-TT0100-"  # torrent-tpu 0.1 (client.ts:19-31 analogue)


def generate_peer_id() -> bytes:
    suffix = "".join(random.choices(string.ascii_letters + string.digits, k=12))
    return PEER_ID_PREFIX + suffix.encode("ascii")


@dataclass
class ClientConfig:
    """(client.ts:13-23). Fresh instance per Client — never shared."""

    port: int = 0  # 0 = ephemeral
    host: str = "0.0.0.0"
    peer_id: bytes = field(default_factory=generate_peer_id)
    hasher: str = "cpu"  # 'cpu' | 'tpu' piece verification (BASELINE API)
    # Shared hash-plane scheduler (torrent_tpu.sched): when set, every
    # torrent's resume/self-heal recheck submits to this queue as a
    # low-priority tenant instead of dispatching private device batches
    scheduler: object | None = None
    torrent: TorrentConfig = field(default_factory=TorrentConfig)
    enable_upnp: bool = False  # optional, off by default (SURVEY §7.8)
    # NAT-PMP (RFC 6886): lighter port mapping many gateways speak when
    # they don't do UPnP IGD; also used as a fallback when enable_upnp
    # finds no gateway. Renewed at half-lifetime while running.
    enable_natpmp: bool = False
    resume: bool = True  # fastresume checkpoints for path-based storage
    enable_dht: bool = False  # BEP 5 mainline DHT (net/dht.py)
    dht_port: int = 0  # 0 = ephemeral UDP port
    dht_bootstrap: tuple = ()  # ((host, port), ...) seed nodes
    # Routing-table persistence: node id + good entries saved here on
    # close and rejoined on start (fast restart without public seeds)
    dht_state_path: str = ""
    # BEP 42: reject routing-table nodes whose ids don't derive from
    # their IP (id-targeting defense; off by default for compat)
    dht_enforce_bep42: bool = False
    # BEP 43: mark our queries ro=1 and answer none — for nodes that
    # can't serve (NAT'd/firewalled) and shouldn't pollute peers' tables
    dht_read_only: bool = False
    # Client-global transfer caps in bytes/s (0 = unlimited): one token
    # bucket per direction shared by every torrent (utils/ratelimit.py)
    max_upload_bps: int = 0
    max_download_bps: int = 0
    enable_lsd: bool = False  # BEP 14 local service discovery (net/lsd.py)
    # BEP 34 DNS tracker preferences: expand each announce URL through
    # the host's published TXT record (deny/port/protocol hints) before
    # announcing; resolver trouble fails open. Off by default.
    dns_tracker_prefs: bool = False
    # BEP 29 uTP transport (net/utp.py): accept uTP peers on the same
    # port (UDP) and prefer uTP for outbound dials, TCP fallback
    enable_utp: bool = False
    # CIDR blocklist ("10.0.0.0/8", "2001:db8::/32", single IPs too):
    # matching peers are neither dialed nor accepted
    ip_filter: tuple = ()
    # SOCKS5 proxy URL ("socks5://[user:pass@]host:port", net/socks.py):
    # routes TCP peer dials, HTTP(S) trackers, and metadata fetches.
    # UDP paths can't ride a CONNECT tunnel, so UDP trackers are skipped
    # and outbound uTP + webseeds are disabled (no leaks around it).
    proxy: str = ""


class Client:
    def __init__(self, config: ClientConfig | None = None):
        from torrent_tpu.utils.ratelimit import TokenBucket

        self.config = config or ClientConfig()
        self.torrents: dict[bytes, Torrent] = {}
        self._server: asyncio.AbstractServer | None = None
        self._verifier_cache: dict[int, object] = {}
        self.external_ip: str | None = None
        self.port: int | None = None  # assigned by start()
        self.dht = None  # net.dht.DHTNode when enable_dht
        self._dht_maintenance: asyncio.Task | None = None
        self.upload_bucket = TokenBucket(self.config.max_upload_bps)
        self.download_bucket = TokenBucket(self.config.max_download_bps)
        self.lsd = None  # net.lsd.LocalServiceDiscovery when enable_lsd
        self.utp = None  # net.utp.UtpEndpoint when enable_utp
        self._natpmp_task: asyncio.Task | None = None
        # test seams: a fake gateway address/port instead of the route table
        self._natpmp_gateway: str | None = None
        self._natpmp_port: int = 5351
        # the port the gateway actually forwards (differs from self.port
        # when the NAT-PMP suggestion wasn't honored); announces use it
        self.external_port: int | None = None
        if self.config.ip_filter:
            from torrent_tpu.net.ipfilter import IpFilter

            self.ip_filter = IpFilter(self.config.ip_filter)
        else:
            self.ip_filter = None
        if self.config.proxy:
            from torrent_tpu.net.socks import ProxySpec

            self.proxy = ProxySpec.parse(self.config.proxy)  # fails loudly
            # raw-UDP subsystems would announce the client's real address
            # around the tunnel; refusing the combination keeps the
            # no-leak promise explicit instead of silently partial
            if self.config.enable_dht:
                raise ValueError(
                    "enable_dht with a SOCKS5 proxy would announce your real "
                    "address over raw UDP around the tunnel; disable one"
                )
            if self.config.enable_lsd:
                raise ValueError(
                    "enable_lsd with a SOCKS5 proxy would multicast your real "
                    "address on the LAN; disable one"
                )
        else:
            self.proxy = None
        self.dns_prefs = None  # net.dnsprefs.TrackerPrefs when enabled
        if self.config.dns_tracker_prefs:
            if self.proxy is not None:
                # the TXT lookup is raw UDP from THIS host: under a SOCKS
                # proxy it would leak tracker hostnames around the tunnel
                # the user configured for exactly that traffic — and a
                # UDP-only preference record would route announces onto a
                # transport the proxy cannot carry. Fail safe: disabled.
                log.warning(
                    "dns_tracker_prefs disabled: BEP 34 lookups would "
                    "bypass the SOCKS proxy"
                )
            else:
                from torrent_tpu.net.dnsprefs import TrackerPrefs

                # one shared cache for every torrent's tracker rotation
                self.dns_prefs = TrackerPrefs()

    async def __aenter__(self) -> "Client":
        try:
            await self.start()
        except BaseException:
            # __aexit__ never runs when __aenter__ raises: release the
            # listener/mappings a partial start() may have acquired
            await self.close()
            raise
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # ------------------------------------------------------------- startup

    async def start(self) -> None:
        """listen → learn real port → (optional UPnP) → accept loop
        (client.ts:69-83)."""
        self._server = await asyncio.start_server(
            self._accept, self.config.host, self.config.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.config.enable_upnp:
            # before DHT: a learned external IP lets the DHT node mint a
            # BEP 42-compliant id at construction
            try:
                from torrent_tpu.net.upnp import get_ip_addrs_and_map_port

                ips = await get_ip_addrs_and_map_port(self.port)
                self.external_ip = ips.external_ip
            except Exception as e:  # UPnP is best-effort
                log.warning("UPnP setup failed: %s", e)
        if self.config.enable_natpmp and self.external_ip is None:
            # explicit: worth blocking start briefly — the learned
            # external IP lets the DHT mint a BEP 42 id below
            await self._try_natpmp()
        elif self.config.enable_upnp and self.external_ip is None:
            # fallback after a failed UPnP probe: run in the background —
            # a gateway speaking NEITHER protocol would otherwise add the
            # whole retry ladder (~8 s) to every start
            self._natpmp_task = asyncio.create_task(self._try_natpmp())
        if self.config.enable_dht:
            from torrent_tpu.net.dht import DHTNode

            from torrent_tpu.net.dht import bep42_valid

            saved_id, saved_nodes = (
                DHTNode.load_state(self.config.dht_state_path)
                if self.config.dht_state_path
                else (None, [])
            )
            # a persisted id keeps our routing-table position (and other
            # nodes' entries for us) across restarts; it survives a
            # learned external IP as long as it is still BEP 42-valid
            # for it (the common unchanged-IP case), else a compliant id
            # is minted fresh
            keep_id = saved_id is not None and (
                self.external_ip is None or bep42_valid(saved_id, self.external_ip)
            )
            self.dht = await DHTNode(
                node_id=saved_id if keep_id else None,
                port=self.config.dht_port,
                host=self.config.host,
                enforce_bep42=self.config.dht_enforce_bep42,
                external_ip=self.external_ip,
                read_only=self.config.dht_read_only,
            ).start()
            seeds = [tuple(a) for a in self.config.dht_bootstrap] + saved_nodes
            if seeds:
                await self.dht.bootstrap(seeds)
            # table housekeeping for quiet nodes: stale pings + bucket
            # refresh + peer-store expiry (net/dht.py maintain_once)
            self._dht_maintenance = asyncio.create_task(self.dht.maintain())
        if self.config.enable_lsd:
            try:
                from torrent_tpu.net.lsd import LocalServiceDiscovery

                self.lsd = LocalServiceDiscovery(self.port, self._on_lsd_peer)
                await self.lsd.start()
            except Exception as e:  # multicast may be unavailable
                log.warning("LSD setup failed: %s", e)
                self.lsd = None
        if self.config.enable_utp:
            from torrent_tpu.net.utp import create_utp_endpoint

            # same port number as the TCP listener, UDP side — inbound
            # uTP streams run the ordinary BitTorrent handshake through
            # the same accept path as TCP connections
            self.utp = await create_utp_endpoint(
                self.config.host, self.port, on_accept=self._accept
            )

    async def _try_natpmp(self) -> None:
        """Best-effort NAT-PMP mapping + external IP, renewed at half of
        each GRANTED lifetime (gateways may shorten grants over time)."""
        from torrent_tpu.net import natpmp

        gateway = self._natpmp_gateway or natpmp.default_gateway()
        if gateway is None:
            log.warning("NAT-PMP: no default gateway found")
            return
        try:
            self.external_ip = await natpmp.external_address(
                gateway, port=self._natpmp_port
            )
            granted, lifetime = await natpmp.map_port(
                gateway, self.port, tcp=True, port=self._natpmp_port
            )
            await natpmp.map_port(
                gateway, self.port, external_port=granted, tcp=False,
                port=self._natpmp_port,
            )  # uTP/DHT share the port number over UDP
        except (natpmp.NatPmpError, OSError) as e:
            log.warning("NAT-PMP setup failed: %s", e)
            return
        if granted != self.port:
            # the suggestion is only a hint — announces must advertise
            # the port the gateway actually forwards
            self.external_port = granted
        self._natpmp_gateway = gateway
        log.info(
            "NAT-PMP: external %s, port %d -> %d", self.external_ip, self.port, granted
        )

        async def renew():
            life = lifetime
            ext = granted
            while True:
                await asyncio.sleep(min(3600, max(30, life // 2)))
                try:
                    ext, life = await natpmp.map_port(
                        gateway, self.port, external_port=ext, tcp=True,
                        port=self._natpmp_port,
                    )
                    await natpmp.map_port(
                        gateway, self.port, external_port=ext, tcp=False,
                        port=self._natpmp_port,
                    )
                except (natpmp.NatPmpError, OSError) as e:
                    log.warning("NAT-PMP renewal failed: %s", e)

        self._natpmp_task = asyncio.create_task(renew())

    async def _natpmp_unmap(self) -> None:
        """Delete our mappings (RFC 6886 §3.4): the gateway must not keep
        forwarding to a dead socket for the rest of the lease."""
        from torrent_tpu.net import natpmp

        if self._natpmp_gateway is None or self.port is None:
            return
        for tcp in (True, False):
            try:
                await natpmp.map_port(
                    self._natpmp_gateway, self.port, lifetime=0, tcp=tcp,
                    port=self._natpmp_port,
                )
            except (natpmp.NatPmpError, OSError):
                pass

    def _on_lsd_peer(self, info_hash: bytes, addr: tuple[str, int]) -> None:
        """BEP 14 callback: a local client announced this swarm."""
        torrent = self.torrents.get(info_hash)
        if torrent is not None and not torrent.private:
            from torrent_tpu.net.types import AnnouncePeer

            torrent._connect_new_peers([AnnouncePeer(ip=addr[0], port=addr[1])])

    async def close(self) -> None:
        for torrent in list(self.torrents.values()):
            await torrent.stop()
        self.torrents.clear()
        if self.lsd is not None:
            self.lsd.close()
            self.lsd = None
        if self.utp is not None:
            self.utp.close()
            self.utp = None
        if self._dht_maintenance is not None:
            self._dht_maintenance.cancel()
            self._dht_maintenance = None
        if self._natpmp_task is not None:
            self._natpmp_task.cancel()
            self._natpmp_task = None
            await self._natpmp_unmap()
        if self.dht is not None:
            if self.config.dht_state_path:
                try:
                    self.dht.save_state(self.config.dht_state_path)
                except OSError as e:
                    log.warning("dht state save failed: %s", e)
            self.dht.close()
            self.dht = None
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    # ------------------------------------------------------------ torrents

    def _verifier_for(self, piece_length: int):
        """One shared TPUVerifier per piece geometry (compiled once)."""
        if self.config.hasher != "tpu":
            return None
        v = self._verifier_cache.get(piece_length)
        if v is None:
            from torrent_tpu.models.verifier import TPUVerifier

            v = TPUVerifier(
                piece_length=piece_length,
                batch_size=self.config.torrent.verify_batch_size,
            )
            self._verifier_cache[piece_length] = v
        return v

    async def add(
        self,
        metainfo: Metainfo,
        storage: Storage | StorageMethod | str,
        wanted_files: list[int] | None = None,
        _adopt_from: tuple = (),  # Torrent donors (BEP 39 predecessor)
    ) -> Torrent:
        """Register + start a torrent (client.ts:53-67).

        ``storage`` may be a ready Storage, a StorageMethod, or a
        directory path (convenience, mirrors `Client.add(metainfo, dir)`).
        ``metainfo`` may also be a parsed pure-v2 ``MetainfoV2`` (BEP 52):
        it is wrapped into the flat-piece-space session view
        (session/v2.py) and keyed/announced by the truncated SHA-256.
        ``wanted_files`` applies a file selection BEFORE the torrent
        starts (out-of-range indices dropped) — selecting after start
        would let pieces of unselected files be requested and written
        during the announce/connect window.
        """
        if self.port is None:
            raise RuntimeError("Client.start() must be awaited before add()")
        from torrent_tpu.codec.metainfo_v2 import MetainfoV2

        if isinstance(metainfo, MetainfoV2):
            from torrent_tpu.session.v2 import v2_session_meta

            metainfo = v2_session_meta(metainfo)
        if metainfo.info_hash in self.torrents:
            raise ValueError("torrent already added")
        resume_store = None
        if isinstance(storage, str):
            if self.config.resume:
                from torrent_tpu.session.resume import FsResumeStore

                resume_store = FsResumeStore(storage)
            storage = Storage(FsStorage(storage), metainfo.info)
        elif not isinstance(storage, Storage):
            storage = Storage(storage, metainfo.info)
        # Derive (never mutate) the per-torrent config: the client-level
        # hasher choice is applied to a copy, so a TorrentConfig shared by
        # the caller across clients stays untouched (the same
        # shared-mutation bug class the reference had, SURVEY §8.2).
        torrent_config = dataclasses.replace(
            self.config.torrent,
            hasher=self.config.hasher,
            scheduler=(
                self.config.scheduler
                if self.config.scheduler is not None
                else self.config.torrent.scheduler
            ),
        )
        torrent = Torrent(
            metainfo=metainfo,
            storage=storage,
            peer_id=self.config.peer_id,
            port=self.external_port or self.port,
            config=torrent_config,
            # the shared TPUVerifier is the SHA-1 plane — v2 pieces verify
            # against merkle roots instead (session/torrent.py v2 branch)
            verifier=None
            if getattr(metainfo.info, "v2", False)
            else self._verifier_for(metainfo.info.piece_length),
            resume_store=resume_store,
            dht=self.dht,
            upload_bucket=self.upload_bucket,
            download_bucket=self.download_bucket,
            external_ip=self.external_ip,
            utp_dial=self.utp.dial if self.utp is not None else None,
            ip_filter=self.ip_filter,
            proxy=self.proxy,
            dns_prefs=self.dns_prefs,
        )
        self.torrents[metainfo.info_hash] = torrent
        if wanted_files is not None:
            n_files = len(torrent.file_ranges())
            await torrent.select_files(
                [i for i in wanted_files if 0 <= i < n_files]
            )
        await self._adopt_similar(torrent, donor_torrents=tuple(_adopt_from))
        await torrent.start()
        if self.lsd is not None and not torrent.private:
            self.lsd.register(metainfo.info_hash)  # BEP 27: never private
        return torrent

    async def _adopt_similar(
        self,
        torrent: Torrent,
        donor_torrents: tuple[Torrent, ...] = (),
    ) -> None:
        """BEP 38 local-data reuse: pre-fill the new torrent's storage
        from identical files of already-registered torrents.

        Torrents are related when either names the other in ``similar``
        or they share a ``collections`` entry. Files match on (basename,
        size) — BEP 38's v1 criterion — and only fully-verified donor
        spans are copied, BEFORE ``start()`` so the normal recheck adopts
        the bytes (boundary pieces spanning non-shared neighbours simply
        fail the hash and download as usual). Writes go through the
        storage method directly: ``Storage.set``'s duplicate-write marks
        must stay clear so the swarm can overwrite an adopted span whose
        piece hash didn't pan out.
        """
        meta = torrent.metainfo
        # session-meta wrappers (pure-v2) may not carry the BEP 38
        # surface; they can still be adopted INTO when a donor names them
        hints = set(getattr(meta, "similar", ()) or ())
        cols = set(getattr(meta, "collections", ()) or ())
        # explicit donors (BEP 39: the already-STOPPED predecessor — it
        # must not be registered/serving while the successor overwrites
        # shared files, so it can't be found via self.torrents)
        donors = list(donor_torrents)
        for d in self.torrents.values():
            if d is torrent:
                continue
            dm = d.metainfo
            related = (
                dm.info_hash in hints
                or meta.info_hash in (getattr(dm, "similar", ()) or ())
                or (cols and cols.intersection(getattr(dm, "collections", ()) or ()))
            )
            if related:
                donors.append(d)
        if not donors:
            return

        def files_of(t):
            if t.info.files is None:
                off, ln = t.file_ranges()[0]
                return [(t.info.name, off, ln)]
            out = []
            for fe, (off, ln) in zip(t.info.files, t.file_ranges()):
                if getattr(fe, "pad", False) or ln == 0:
                    continue
                out.append((fe.path[-1], off, ln))
            return out

        # donor file index; first fully-verified donor span per key wins
        index: dict[tuple[str, int], tuple[Torrent, int]] = {}
        for d in donors:
            plen = d.info.piece_length
            have = d.bitfield.as_numpy()
            for name, off, ln in files_of(d):
                key = (name, ln)
                if key in index:
                    continue
                lo, hi = off // plen, -(-(off + ln) // plen)
                if have[lo:hi].all():
                    index[key] = (d, off)

        jobs = []  # (donor_storage, donor_off, our_off, length)
        plen_t = torrent.info.piece_length
        prio = torrent._piece_priority
        for name, off, ln in files_of(torrent):
            hit = index.get((name, ln))
            if hit is None:
                continue
            donor, d_off = hit
            if self._same_backing_file(donor.storage, d_off, torrent.storage, off):
                continue  # in-place update: the bytes are already there;
                # the recheck adopts them without a self-copy
            # Copy only spans under WANTED pieces: a file the user
            # deselected contributes just the boundary bytes a wanted
            # neighbour's piece needs, not its full (possibly huge) body.
            lo, hi = off // plen_t, -(-(off + ln) // plen_t)
            run_start = None
            prev = None

            def flush(a, b):
                start = max(off, (lo + a) * plen_t)
                end = min(off + ln, (lo + b + 1) * plen_t)
                if end > start:
                    jobs.append(
                        (donor.storage, d_off + (start - off), start, end - start)
                    )

            for w in range(hi - lo):
                if prio[lo + w] <= 0:
                    continue
                if run_start is None:
                    run_start = w
                elif w != prev + 1:
                    flush(run_start, prev)
                    run_start = w
                prev = w
            if run_start is not None:
                flush(run_start, prev)
        if not jobs:
            return

        def copy_spans():
            copied = 0
            for donor_storage, d_off, t_off, length in jobs:
                try:
                    pos = 0
                    while pos < length:
                        n = min(1 << 20, length - pos)
                        data = donor_storage.get(d_off + pos, n)
                        p = 0
                        for path, foff, chunk in torrent.storage.segments(
                            t_off + pos, len(data)
                        ):
                            if path is not None:
                                torrent.storage.method.set(
                                    path, foff, data[p : p + chunk]
                                )
                            p += chunk
                        pos += n
                    copied += length
                except Exception as e:  # best-effort: recheck is the gate
                    log.warning("BEP 38 adoption failed mid-file: %s", e)
            return copied

        copied = await asyncio.to_thread(copy_spans)
        if copied:
            log.info(
                "BEP 38: adopted %d bytes across %d files from %d related torrents",
                copied,
                len(jobs),
                len(donors),
            )

    @staticmethod
    def _same_backing_file(
        donor_storage: Storage, d_off: int, storage: Storage, t_off: int
    ) -> bool:
        """True when both offsets resolve to the same on-disk file (an
        in-place BEP 39 update over the old torrent's directory) — a
        copy would just rewrite the file onto itself."""
        try:
            d_seg = next(iter(donor_storage.segments(d_off, 1)))
            t_seg = next(iter(storage.segments(t_off, 1)))
        except StopIteration:
            return False
        if d_seg[0] is None or t_seg[0] is None:
            return False  # BEP 47 pad span: nothing on disk to compare
        dm, tm = donor_storage.method, storage.method
        if dm is tm and d_seg[0] == t_seg[0]:
            return True
        if isinstance(dm, FsStorage) and isinstance(tm, FsStorage):
            try:
                import os

                return os.path.samefile(
                    dm._abspath(d_seg[0]), tm._abspath(t_seg[0])
                )
            except OSError:
                return False
        return False

    async def check_for_update(self, torrent: Torrent):
        """BEP 39: fetch the torrent's ``update-url``; a metainfo with a
        DIFFERENT infohash means an update exists (None = current, or no
        update-url). Delegates to module-level :func:`fetch_update` with
        the client's proxy so the poll never leaks the real IP."""
        return await fetch_update(torrent.metainfo, proxy=self.proxy)

    @staticmethod
    def _carry_selection(old: Torrent, new_meta) -> list[int] | None:
        """Map the old torrent's file selection onto the successor by
        relative path: a file the user deselected stays deselected if it
        reappears; new files default to wanted. None = no selection to
        carry (everything was wanted)."""
        if not any(p <= 0 for p in old.file_priorities.values()):
            return None

        def paths(info):
            if getattr(info, "files", None) is None:
                return [(info.name,)]
            return [tuple(fe.path) for fe in info.files]

        old_unwanted = {
            p
            for i, p in enumerate(paths(old.info))
            if old.file_priorities.get(i, 1) <= 0
        }
        new_info = getattr(new_meta, "info", new_meta)
        return [
            i for i, p in enumerate(paths(new_info)) if p not in old_unwanted
        ]

    async def apply_update(
        self,
        torrent: Torrent,
        new_meta: Metainfo | None = None,
        storage: Storage | StorageMethod | str | None = None,
        wanted_files: list[int] | None = None,
    ) -> Torrent | None:
        """BEP 39: switch to the updated torrent. Fetches the update when
        ``new_meta`` is None (returning None if already current), adds it
        with the old torrent as a BEP 38 adoption donor — unchanged files
        carry over without touching the swarm — then removes the old one.
        ``storage`` defaults to the old torrent's directory (in-place
        update) when it lives on the filesystem. The old torrent's file
        selection carries over by relative path (a deselected 100 GB file
        must not start downloading because the dataset was re-published);
        pass ``wanted_files`` to override."""
        if new_meta is None:
            new_meta = await self.check_for_update(torrent)
            if new_meta is None:
                return None
        if storage is None:
            method = torrent.storage.method
            if isinstance(method, FsStorage):
                storage = method.root
            else:
                raise ValueError(
                    "apply_update needs an explicit storage for non-filesystem torrents"
                )
        if wanted_files is None:
            wanted_files = self._carry_selection(torrent, new_meta)
        # Deregister + stop the predecessor BEFORE the successor starts:
        # the two share files in an in-place update, and a still-serving
        # old seed would hand out offsets the new download is rewriting
        # (peers would hash-fail those pieces and strike us). It stays
        # available as an adoption donor by reference; on a failed add it
        # is re-registered and restarted.
        await self.remove(torrent.metainfo.info_hash)
        try:
            new_torrent = await self.add(
                new_meta,
                storage,
                wanted_files=wanted_files,
                _adopt_from=(torrent,),
            )
        except BaseException:
            self.torrents[torrent.metainfo.info_hash] = torrent
            # remove() unregistered the predecessor from local-service
            # discovery; a rollback must restore that announcement too
            if self.lsd is not None and not torrent.private:
                self.lsd.register(torrent.metainfo.info_hash)
            await torrent.start()
            raise
        # successful switch: the predecessor's fastresume checkpoint is
        # stale forever (its info hash will never be added again here)
        if torrent.resume_store is not None:
            torrent.resume_store.delete(torrent.metainfo.info_hash)
        return new_torrent

    async def add_torrent_bytes(
        self,
        data: bytes,
        storage: "Storage | StorageMethod | str",
        require_signed: "tuple[str, bytes] | None" = None,
        wanted_files: "list[int] | None" = None,
    ) -> "Torrent":
        """Parse raw .torrent bytes (v1 OR pure v2) and ``add`` them —
        the library-level twin of the CLI's auto-detecting load path.

        ``require_signed = (signer, trusted_pub)`` applies the BEP 35
        gate on the RAW bytes before any parse result is trusted (the
        same check ``download/update/feed --require-signed`` run);
        refusal raises ValueError and nothing is registered.
        """
        if require_signed is not None:
            from torrent_tpu.codec import signing

            signer, pub = require_signed
            signing.ensure_signed(data, signer, pub)
        from torrent_tpu.codec.metainfo import parse_any_metainfo

        parsed = parse_any_metainfo(data)
        if parsed is None:
            raise ValueError("not a valid .torrent (neither v1 nor v2)")
        return await self.add(parsed[0], storage, wanted_files=wanted_files)

    async def add_hybrid(
        self, torrent_bytes: bytes, storage_dir: str
    ) -> "tuple[Torrent, Torrent]":
        """Register a BEP 52 hybrid torrent under BOTH its identities —
        the SHA-1 infohash (v1 swarm) and the truncated SHA-256 (v2
        swarm) — seeding/downloading the same directory. Returns
        ``(v1_torrent, v2_torrent)``.

        The v2 view's piece space is file-aligned while v1's is packed,
        but hybrids carry BEP 47 pad files that make the two byte layouts
        coincide on disk, so one directory serves both swarms.
        """
        from torrent_tpu.codec.metainfo import parse_metainfo
        from torrent_tpu.codec.metainfo_v2 import parse_metainfo_v2

        m1 = parse_metainfo(torrent_bytes)
        m2 = parse_metainfo_v2(torrent_bytes)
        if m1 is None or m2 is None:
            raise ValueError("not a valid hybrid .torrent (needs both planes)")
        t1 = await self.add(m1, storage_dir)
        try:
            t2 = await self.add(m2, storage_dir)
        except BaseException:
            # all-or-nothing: a half-registered hybrid would leave the v1
            # identity silently announcing with no handle for the caller
            await self.remove(m1.info_hash)
            raise
        return t1, t2

    async def add_magnet(
        self, magnet, storage: Storage | StorageMethod | str
    ) -> Torrent:
        """Join a swarm from a magnet link (BEP 9/10 — reference roadmap
        README.md:39): fetch the info dict from peers, then ``add``.

        ``magnet`` is a ``codec.magnet.Magnet`` or a ``magnet:?...`` URI.
        """
        from torrent_tpu.codec.magnet import Magnet, parse_magnet
        from torrent_tpu.session.metadata import fetch_metadata

        if self.port is None:
            raise RuntimeError("Client.start() must be awaited before add_magnet()")
        if isinstance(magnet, str):
            magnet = parse_magnet(magnet)
        if not isinstance(magnet, Magnet):
            raise TypeError("magnet must be a Magnet or magnet URI string")
        if (
            magnet.mutable_key is not None
            and magnet.info_hash is None
            and magnet.info_hash_v2 is None
        ):
            # BEP 46: resolve the pointer first (no recursion — the
            # resolved magnet carries a concrete btih)
            return await self.add_mutable_magnet(magnet, storage)
        if magnet.wire_hash in self.torrents:
            raise ValueError("torrent already added")
        # Throwaway peer id for the metadata connections: if the fetch
        # socket's EOF hasn't been reaped by the seeder when the real
        # download dials in, our own id would trip its duplicate-peer
        # guard and the data connection would be dropped.
        metainfo = await fetch_metadata(
            magnet,
            peer_id=generate_peer_id(),
            port=self.external_port or self.port,
            dht=self.dht,
            ip_filter=self.ip_filter,
            proxy=self.proxy,
        )
        # BEP 53: the magnet's file selection is applied BEFORE the
        # torrent starts (out-of-range indices dropped — the selection
        # was minted against metadata the author may have mis-remembered;
        # an empty valid set means "download nothing yet")
        torrent = await self.add(
            metainfo,
            storage,
            wanted_files=list(magnet.select_only)
            if magnet.select_only is not None
            else None,
        )
        for ws in magnet.web_seeds:
            torrent.add_web_seed(ws)  # BEP 19 ws= params
        if magnet.peer_addrs:
            # Trackerless magnets (x.pe bootstrap): hand the known peers
            # straight to the scheduler instead of waiting on an announce.
            from torrent_tpu.net.types import AnnouncePeer

            torrent._connect_new_peers(
                [AnnouncePeer(ip=h, port=p) for h, p in magnet.peer_addrs]
            )
        return torrent

    # ---------------------------------------------- BEP 46 mutable magnets

    async def resolve_mutable(self, magnet) -> bytes:
        """Resolve a BEP 46 ``btpk`` magnet to its CURRENT 20-byte
        infohash via the key's BEP 44 mutable item (``{"ih": <hash>}``).

        Raises ValueError when the magnet isn't mutable, the DHT is off,
        the item can't be found, or its payload is malformed.
        """
        import hashlib as _hashlib

        from torrent_tpu.codec.magnet import Magnet, parse_magnet

        if isinstance(magnet, str):
            magnet = parse_magnet(magnet)
        if not isinstance(magnet, Magnet) or magnet.mutable_key is None:
            raise ValueError("not a mutable (urn:btpk) magnet")
        if self.dht is None:
            raise ValueError("mutable magnets need the DHT (enable_dht=True)")
        target = _hashlib.sha1(magnet.mutable_key + magnet.mutable_salt).digest()
        item = await self.dht.get_item(target, salt=magnet.mutable_salt)
        if item is None or item.seq is None:
            raise ValueError("mutable item not found in the DHT")
        v = item.value
        ih = v.get(b"ih") if isinstance(v, dict) else None
        if not isinstance(ih, bytes) or len(ih) != 20:
            raise ValueError("mutable item carries no valid 'ih' pointer")
        return ih

    async def add_mutable_magnet(
        self, magnet, storage: Storage | StorageMethod | str
    ) -> Torrent:
        """BEP 46: resolve the key's current infohash, then join that
        swarm like any magnet (metadata over ut_metadata, BEP 53/19
        params preserved)."""
        from dataclasses import replace

        from torrent_tpu.codec.magnet import Magnet, parse_magnet

        if isinstance(magnet, str):
            magnet = parse_magnet(magnet)
        ih = await self.resolve_mutable(magnet)
        return await self.add_magnet(
            replace(magnet, info_hash=ih, mutable_key=None, mutable_salt=b""),
            storage,
        )

    async def publish_mutable(
        self, secret: bytes, info_hash: bytes, seq: int, salt: bytes = b""
    ) -> tuple[bytes, int]:
        """Publisher side of BEP 46: sign ``{"ih": info_hash}`` as the
        key's BEP 44 mutable item. Returns (dht_target, nodes_stored);
        the shareable URI is ``mutable_magnet_uri(publickey, salt)``.
        Bump ``seq`` on every new revision of the content."""
        if self.dht is None:
            raise ValueError("publishing needs the DHT (enable_dht=True)")
        if len(info_hash) != 20:
            raise ValueError("info_hash must be 20 bytes")
        return await self.dht.put_mutable(secret, {b"ih": info_hash}, seq, salt=salt)

    def status(self) -> dict:
        """Aggregate client observability: per-torrent status plus
        session-wide totals (SURVEY §5 'metrics' — the reference has no
        counters beyond never-updated announce fields, torrent.ts:66-69)."""
        torrents = {
            t.metainfo.info_hash.hex(): t.status() for t in self.torrents.values()
        }
        return {
            "port": self.port,
            "external_ip": self.external_ip,
            "dht": self.dht is not None,
            "lsd": self.lsd is not None,
            "peers": sum(len(t.peers) for t in self.torrents.values()),
            "downloaded": sum(t.downloaded for t in self.torrents.values()),
            "uploaded": sum(t.uploaded for t in self.torrents.values()),
            "upload_cap_bps": self.upload_bucket.rate,
            "download_cap_bps": self.download_bucket.rate,
            "torrents": torrents,
        }

    async def pause_all(self) -> None:
        """Suspend every torrent's transfers (connections kept)."""
        for t in list(self.torrents.values()):
            await t.pause()

    async def resume_all(self) -> None:
        for t in list(self.torrents.values()):
            await t.resume()

    async def remove(self, info_hash: bytes) -> None:
        torrent = self.torrents.pop(info_hash, None)
        if self.lsd is not None:
            self.lsd.unregister(info_hash)
        if torrent:
            await torrent.stop()

    # -------------------------------------------------------------- accept

    async def _accept(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        """Inbound handshake: route on info hash before replying
        (client.ts:85-104).

        MSE/PE auto-detection (net/mse.py): a plaintext BT handshake
        starts with the 20-byte protocol header; anything else under an
        encryption-accepting policy is treated as an MSE initiator and
        answered with the obfuscated handshake, after which the BT
        handshake proceeds over the (possibly RC4) streams.
        """
        from torrent_tpu.net import mse

        policy = self.config.torrent.encryption
        try:
            peername = writer.get_extra_info("peername")
            if (
                peername
                and self.ip_filter is not None
                and self.ip_filter.blocked(peername[0])
            ):
                writer.close()  # blocklisted: drop before reading ANY bytes
                return
            head = await asyncio.wait_for(reader.readexactly(20), timeout=15)
            if head == bytes([len(proto.PROTOCOL_STRING)]) + proto.PROTOCOL_STRING[:19]:
                if policy == "required":
                    writer.close()  # plaintext refused on sight
                    return
                # head IS the whole pstrlen+pstr header: finish phase 1
                # on the raw reader (no wrapper on the plaintext hot path)
                reserved = await asyncio.wait_for(reader.readexactly(8), timeout=15)
                info_hash = await asyncio.wait_for(reader.readexactly(20), timeout=15)
            else:
                if policy == "disabled":
                    writer.close()
                    return
                reader, writer, _skey, _sel = await asyncio.wait_for(
                    mse.respond(
                        reader,
                        writer,
                        head,
                        list(self.torrents.keys()),
                        allow_plaintext=policy != "required",
                    ),
                    timeout=15,
                )
                info_hash, reserved = await asyncio.wait_for(
                    proto.read_handshake_head(reader), timeout=15
                )
            torrent = self.torrents.get(info_hash)
            if torrent is None:
                writer.close()  # unknown torrent: drop pre-reply
                return
            from torrent_tpu.net.extension import extension_reserved

            await proto.send_handshake(
                writer,
                info_hash,
                self.config.peer_id,
                proto.merge_reserved(extension_reserved(), proto.fast_reserved()),
            )
            peer_id = await asyncio.wait_for(proto.read_handshake_peer_id(reader), timeout=15)
            if peer_id == self.config.peer_id:
                writer.close()
                return
            addr = writer.get_extra_info("peername")
            from torrent_tpu.net.types import normalize_peer_host

            await torrent.add_peer(
                peer_id,
                reader,
                writer,
                # dual-stack listeners report v4 peers as ::ffff:a.b.c.d;
                # one canonical form keeps dial dedup and PEX routing sane
                address=(normalize_peer_host(addr[0]), addr[1]) if addr else None,
                reserved=reserved,
                inbound=True,
            )
        except (
            proto.ProtocolError,
            mse.MseError,
            asyncio.TimeoutError,
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
        ):
            writer.close()


async def fetch_update(metainfo, proxy=None, raw_bytes_out: list | None = None):
    """BEP 39 poll, usable without a running Client (the CLI's `update`).

    Fetches ``metainfo.update_url`` (http/https only — the URL is
    untrusted metainfo content, same SSRF stance as webseeds; the body
    size-caps WHILE streaming) and returns the successor's parsed
    metainfo — ``Metainfo`` or ``MetainfoV2`` — or None when there is no
    update-url or the served torrent has the same infohash. Passing
    ``raw_bytes_out`` collects the fetched .torrent bytes (so a caller
    can write the successor to disk verbatim).
    """
    url = getattr(metainfo, "update_url", None)
    if not url:
        return None
    import urllib.parse

    if urllib.parse.urlsplit(url).scheme not in ("http", "https"):
        raise ValueError(f"refusing non-http(s) update-url {url!r}")
    from torrent_tpu.net.tracker import _http_get

    raw = await _http_get(url, timeout=30, proxy=proxy, max_bytes=16 << 20)
    from torrent_tpu.codec.metainfo import parse_any_metainfo

    parsed = parse_any_metainfo(raw)
    if parsed is None:
        raise ValueError("update-url did not serve a valid .torrent")
    new_meta, new_hash = parsed
    if new_hash == metainfo.info_hash:
        return None
    if raw_bytes_out is not None:
        raw_bytes_out.append(raw)
    return new_meta
