"""Torrent session: announce loop, peer loops, scheduler (ref L6: torrent.ts).

The reference's torrent.ts stops at message handling — no piece picker,
no choke policy, no verification, bitfield never updated (SURVEY §8.3).
This is the completed design:

- **announce loop** (torrent.ts:224-244): started/empty/completed events,
  cancellable interval sleep with early wake (``request_peers``), live
  uploaded/downloaded/left counters.
- **scheduler**: rarest-first piece picking over peer availability with
  random tie-break, per-peer request pipelining, endgame mode (duplicate
  the last in-flight blocks, cancel on arrival).
- **choke policy**: periodic round unchoking the top downloaders plus one
  optimistic random peer (BEP 3 semantics).
- **verification hook** (the gap at torrent.ts:183-193): pieces assemble
  in memory, SHA1-verify off-thread (or batched on TPU via the hash
  plane), and only verified pieces are written + ``have``-broadcast.
- **resume-recheck**: ``start()`` runs ``verify_pieces`` (hasher
  'cpu'|'tpu') to rebuild the bitfield before announcing — the subsystem
  the reference lists as roadmap (README.md:34) and the BASELINE north
  star.
"""

from __future__ import annotations

import asyncio
import errno
import hashlib
import random
import time
from collections import Counter
from dataclasses import dataclass, field
from enum import Enum

import numpy as np

from torrent_tpu.codec.metainfo import Metainfo
from torrent_tpu.net import extension as ext
from torrent_tpu.net import protocol as proto
from torrent_tpu.net.constants import DEFAULT_NUM_WANT
from torrent_tpu.net.tracker import TrackerError
from torrent_tpu.net.types import AnnounceEvent, AnnounceInfo
from torrent_tpu.obs.ledger import pipeline_ledger
from torrent_tpu.obs.swarm import swarm_telemetry
from torrent_tpu.session.peer import PeerConnection
from torrent_tpu.storage.piece import (
    BLOCK_SIZE,
    piece_length,
    validate_received_block,
    validate_requested_block,
)
from torrent_tpu.storage.storage import Storage, StorageError
from torrent_tpu.utils.bitfield import Bitfield
from torrent_tpu.utils.ratelimit import TokenBucket
from torrent_tpu.utils.log import get_logger

log = get_logger("session.torrent")

_UNSET = object()  # lazy-field sentinel (None is a meaningful value)

# recv-stage ledger batching: socket-wait seconds and landed block bytes
# flush to the pipeline ledger once per this many events (or 250 ms of
# accumulated wait), so the per-message hot path never takes an obs lock
_RECV_FLUSH_OPS = 32
_RECV_FLUSH_S = 0.25

# failure-detection cardinality caps: both tables key on peer IP, which
# an attacker mints freely — strike/ban state must churn at capacity,
# never grow for the life of the session
MAX_CORRUPTION_IPS = 8192
MAX_BANNED_IPS = 4096


def _wire_payload_bytes(msg) -> int:
    """Payload byte count of a decoded wire message for the per-kind
    telemetry (the variable-length fields; fixed headers are noise)."""
    block = getattr(msg, "block", None)
    if block is not None:
        return len(block)
    raw = getattr(msg, "raw", None)
    if raw is not None:
        return len(raw)
    payload = getattr(msg, "payload", None)
    if payload is not None:
        return len(payload)
    return 0


class TorrentState(Enum):
    """(torrent.ts:39-43 — which the reference never advances, §8.3)."""

    STOPPED = "stopped"
    CHECKING = "checking"
    DOWNLOADING = "downloading"
    SEEDING = "seeding"


class AcceptGate:
    """Admission + idle-reclamation bookkeeping for the accept path:
    ``capacity`` slots, a slot's holder evicted once idle for
    ``idle_after`` units of the caller's clock. This is the defense
    slowloris probes — connections that never make progress must be
    reclaimed, not held forever.

    Clock-agnostic on purpose: the live session feeds it monotonic
    seconds (``idle_after`` = ``peer_timeout``) while the scenario
    plane (``scenario/actors.py``) drives the SAME class with virtual
    ticks, so the chaos suite exercises exactly the eviction policy
    production runs."""

    def __init__(self, capacity: int, idle_after: float, per_ip: int = 0):
        self.capacity = capacity
        self.idle_after = idle_after
        # per-address admission clamp (0 = off): a stampede from one
        # address — NAT abuse or a sybil fleet — can hold at most this
        # many slots, leaving the rest for the crowd
        self.per_ip = int(per_ip)
        self.slots: dict = {}  # key -> last activity instant
        self._ips: dict = {}  # key -> admitting address
        self._ip_counts: dict = {}  # address -> live slots
        self.evicted_idle = 0
        self.rejected_per_ip = 0
        self.rejected_capacity = 0
        # why the latest connect() returned False ("per_ip"/"capacity")
        self.last_reject: str | None = None

    def connect(self, key, now, ip=None) -> bool:
        """Admit (or refresh) ``key``; False when every slot is held or
        ``ip`` already holds :attr:`per_ip` slots."""
        if key in self.slots:
            self.slots[key] = now
            return True
        if (
            self.per_ip > 0
            and ip is not None
            and self._ip_counts.get(ip, 0) >= self.per_ip
        ):
            self.rejected_per_ip += 1
            self.last_reject = "per_ip"
            return False
        if len(self.slots) >= self.capacity:
            self.rejected_capacity += 1
            self.last_reject = "capacity"
            return False
        self.slots[key] = now
        if ip is not None:
            self._ips[key] = ip
            # one entry per admitting address of a LIVE slot (released in
            # _forget_ip): cardinality ≤ the slot capacity checked above
            self._ip_counts[ip] = self._ip_counts.get(ip, 0) + 1  # bounded-by: capacity
        return True

    def touch(self, key, now) -> None:
        """Record activity for an already-admitted key (no-op for
        unknown keys: the caller's peer map is authoritative)."""
        if key in self.slots:
            self.slots[key] = now

    def _forget_ip(self, key) -> None:
        ip = self._ips.pop(key, None)
        if ip is not None:
            left = self._ip_counts.get(ip, 0) - 1
            if left > 0:
                self._ip_counts[ip] = left
            else:
                self._ip_counts.pop(ip, None)

    def release(self, key) -> None:
        self.slots.pop(key, None)
        self._forget_ip(key)

    def sweep(self, now) -> list:
        """Evict every slot idle past ``idle_after``; returns the
        evicted keys (admission order — dict order is deterministic)."""
        dead = [
            k for k, last in self.slots.items()
            if now - last >= self.idle_after
        ]
        for k in dead:
            del self.slots[k]
            self._forget_ip(k)
        self.evicted_idle += len(dead)
        return dead


@dataclass
class _PartialPiece:
    """A piece being assembled in memory before verification."""

    index: int
    length: int
    buffer: bytearray
    received: set[int] = field(default_factory=set)  # block offsets
    # (peer_id, ip) of every block contributor — corruption accounting
    # must survive the contributor disconnecting, so the IP rides along
    contributors: set[tuple[bytes, str | None]] = field(default_factory=set)
    # Reserved by a webseed fetch: the block scheduler must not hand this
    # piece to peers (they'd race the HTTP fetch), except in endgame.
    webseed: bool = False

    @property
    def complete(self) -> bool:
        return len(self.received) * BLOCK_SIZE >= self.length


@dataclass
class TorrentConfig:
    max_peers: int = 50
    pipeline_depth: int = 16  # outstanding requests per peer
    max_corrupt_pieces: int = 3  # hash failures before a peer is banned
    unchoke_slots: int = 3  # + 1 optimistic
    choke_interval: float = 10.0
    snub_timeout: float = 30.0  # no block for this long → free its requests
    keepalive_interval: float = 100.0
    peer_timeout: float = 240.0
    # Slot recycling: when the peer list is full, a NEW connection may
    # evict a mutually-uninterested idle peer (nothing in flight either
    # way) that has been connected at least this long — a swarm larger
    # than max_peers must rotate through the slots, not starve. The
    # grace keeps fresh connections from being evicted before they can
    # express interest (and bounds eviction thrash).
    evict_grace: float = 15.0
    announce_retry: float = 30.0
    hasher: str = "cpu"  # 'cpu' | 'tpu' — resume-recheck + batch verify
    verify_batch_size: int = 256
    # Shared hash-plane scheduler (torrent_tpu.sched.HashPlaneScheduler).
    # When set, resume/self-heal rechecks ride the shared verify queue as
    # the low-priority "selfheal" tenant (DRR weight below) instead of
    # dispatching their own device batches — swarm background traffic can
    # never starve a foreground CLI verify or bridge client.
    scheduler: object | None = None
    selfheal_weight: float = 0.25
    dht_interval: float = 300.0  # DHT announce/lookup cadence
    pex_interval: float = 60.0  # BEP 11 peer-exchange cadence
    webseed_retry: float = 15.0  # backoff after a webseed failure
    # In-order piece picking for streaming/preview playback (rarest-first
    # otherwise; file priorities still outrank the order either way)
    sequential: bool = False
    # Whole pieces cached on the serve path (LRU): a piece is requested
    # as 16+ sequential blocks, so this turns 16 preads into 1. Memory
    # cost = serve_cache_pieces * piece_length PER TORRENT; the cache
    # disables itself for pieces over serve_cache_max_piece (whole-piece
    # reads would be 1000x amplification for one-block fetches there)
    serve_cache_pieces: int = 8
    serve_cache_max_piece: int = 2 * 1024 * 1024
    webseed_concurrency: int = 2  # parallel piece fetches per webseed
    webseed_max_failures: int = 5  # consecutive bad pieces → URL disabled
    # BEP 16 super-seeding: reveal pieces one-by-one via targeted Haves
    # and advance only when ANOTHER peer echoes the piece back — the
    # initial seed uploads ≈1 copy instead of N partial copies
    super_seed: bool = False
    super_seed_outstanding: int = 2  # unconfirmed pieces per peer
    # MSE/PE protocol encryption (net/mse.py): 'disabled' = plaintext
    # only; 'enabled' = accept both inbound, dial plaintext first with an
    # encrypted retry (interops with encryption-requiring peers);
    # 'required' = RC4 only, both directions
    encryption: str = "enabled"
    # Per-torrent transfer caps in bytes/s (0 = unlimited), layered
    # UNDER the client-global buckets: a transfer waits on both, so the
    # tighter of the two limits wins
    max_upload_bps: int = 0
    max_download_bps: int = 0
    # ---- serve plane (torrent_tpu/serve_plane/) -----------------------
    # AcceptGate per-address admission clamp (0 = off): a stampede from
    # one address can hold at most this many slots. Off by default —
    # loopback test rigs and NATed swarms legitimately share addresses.
    per_ip_limit: int = 0
    # reactor pool: worker count, per-peer pending-request bound (past
    # it the session answers BEP 6 rejects — bounded hostile demand),
    # and requests drained per peer per turn (round-robin fairness)
    serve_reactor_workers: int = 4
    serve_queue_depth: int = 64
    serve_batch: int = 8
    # DRR choke-economics quantum: deficit bytes a weight-1.0 candidate
    # accrues per unchoke round (one 16 KiB block by default)
    choke_quantum: int = 16384

    def __post_init__(self):
        if self.encryption not in ("disabled", "enabled", "required"):
            raise ValueError(
                f"encryption must be disabled|enabled|required, got {self.encryption!r}"
            )


# Piece sizes at or below this run their hash/pread/pwrite INLINE on the
# event loop instead of via asyncio.to_thread: a thread hop costs ~0.5-2 ms
# of scheduling latency while sha1/pread of 64 KiB is tens of µs — for
# small-piece torrents the hops dominate end-to-end throughput (measured:
# 4 KiB-piece swarms went from ~150 to >1000 pieces/s aggregate).
INLINE_IO_MAX = 64 * 1024


class Torrent:
    def __init__(
        self,
        metainfo: Metainfo,
        storage: Storage,
        peer_id: bytes,
        port: int,
        config: TorrentConfig | None = None,
        verifier=None,  # optional TPUVerifier to share across torrents
        resume_store=None,  # optional session/resume.py store
        dht=None,  # optional net.dht.DHTNode for trackerless discovery
        upload_bucket=None,  # optional utils/ratelimit.TokenBucket (client-global)
        download_bucket=None,
        external_ip=None,  # our public address, for BEP 40 dial ordering
        utp_dial=None,  # optional BEP 29 dialer: async (host, port) -> streams
        ip_filter=None,  # optional net.ipfilter.IpFilter (client-global)
        proxy=None,  # optional net.socks.ProxySpec: TCP dials + HTTP trackers
        dns_prefs=None,  # optional net.dnsprefs.TrackerPrefs (BEP 34)
    ):
        from torrent_tpu.net.multitracker import TrackerList, parse_announce_list

        self.metainfo = metainfo
        self.info = metainfo.info
        self.storage = storage
        self.peer_id = peer_id
        self.port = port
        self.config = config or TorrentConfig()
        self.verifier = verifier
        self.resume_store = resume_store
        self.dht = dht
        self.upload_bucket = upload_bucket
        self.download_bucket = download_bucket
        # per-torrent caps layered under the client-global buckets
        self.own_upload_bucket = TokenBucket(self.config.max_upload_bps)
        self.own_download_bucket = TokenBucket(self.config.max_download_bps)
        self.external_ip = external_ip
        # a CONNECT proxy cannot carry uTP datagrams; racing uTP beside
        # it would leak the peer address around the tunnel
        self._utp_dial = utp_dial if proxy is None else None
        self.ip_filter = ip_filter
        self.proxy = proxy
        self.trackers = TrackerList(
            metainfo.announce,
            parse_announce_list(metainfo.raw),
            proxy=proxy,
            dns_prefs=dns_prefs,
        )

        # BEP 52 pure-v2 torrent (session/v2.py): 32-byte merkle piece
        # digests, file-aligned piece space, truncated-sha256 wire hash
        self.v2 = getattr(self.info, "v2", False)
        # BEP 16 super-seeding state (lazily sized on first assignment)
        self._ss_active = bool(self.config.super_seed)
        self._ss_spread: np.ndarray | None = None  # bool[n]: echoed back
        self._ss_assigned: np.ndarray | None = None  # int32[n]: live grants
        self.state = TorrentState.STOPPED
        self.bitfield = Bitfield(self.info.num_pieces)
        self.peers: dict[bytes, PeerConnection] = {}
        # slot admission + slowloris idle-reclamation bookkeeping; the
        # peers dict stays authoritative — the gate mirrors it so the
        # eviction policy (and its counter) is the same object the
        # scenario plane attacks
        self._accept_gate = AcceptGate(
            self.config.max_peers,
            self.config.peer_timeout,
            per_ip=self.config.per_ip_limit,
        )
        self._partials: dict[int, _PartialPiece] = {}
        # TPU ingest-verification micro-batching (see _verify_piece_data)
        self._verify_pending: list = []
        self._verify_flushing = False
        self._tasks: set[asyncio.Task] = set()
        # one live fetch loop per webseed/httpseed URL (see
        # _spawn_seed_loops re-entrancy)
        self._seed_loop_tasks: dict[str, asyncio.Task] = {}
        self._wake = asyncio.Event()
        self._stopping = False
        self._endgame = False
        self._pending_completed = False  # BEP 3 `completed` owed to tracker
        self._completed_reported = False  # latch: `completed` sent at most once
        self._dialing: set[tuple[str, int]] = set()
        # Failure detection: corruption strikes accumulate per IP (so a
        # poisoner can't evade by cycling connections) and decay when a
        # piece the address contributed to verifies (so honest peers that
        # co-contributed with a poisoner shed the suspicion). At the
        # threshold the address is banned for the session.
        self._corruption: Counter = Counter()  # ip -> strikes
        self._banned: dict[str, None] = {}  # by IP, insertion-ordered
        # Incremental scheduler state: per-piece availability counts, a
        # rarity-ordered pick queue (rebuilt lazily when dirty), and a
        # multiset of blocks in flight across all peers — keeps block
        # ingest O(1)-ish instead of rescanning every peer bitfield.
        self._avail = np.zeros(self.info.num_pieces, dtype=np.int32)
        self._rarity_order: list[int] = []
        # Per-piece download priority (no reference counterpart — the
        # reference downloads everything or nothing). 0 = skip, higher =
        # sooner; derived from per-file priorities via set_file_priorities.
        self._piece_priority = np.ones(self.info.num_pieces, dtype=np.int8)
        # effective per-file priorities (empty until a selection is set:
        # everything wanted at the default 1)
        self.file_priorities: dict[int, int] = {}
        # streaming: pre-boost priority snapshot, active reader windows
        # (token -> (first_piece, n)), and per-piece completion events
        # for parked readers (created on demand, popped on set)
        self._stream_base: np.ndarray | None = None
        self._stream_positions: dict[object, tuple[int, int]] = {}
        self._piece_events: dict[int, asyncio.Event] = {}
        # last persisted partial set (serialized form) — carried forward
        # by periodic checkpoints until the pieces complete
        self._saved_partials: dict[int, tuple[bytes, bytes]] = {}
        # selection updates serialize (they suspend for the partfile
        # sweep; interleaving would desync priorities from routing)
        self._selection_lock = asyncio.Lock()
        # cached count of wanted-but-missing pieces: _fill_pipeline gates
        # on it per block, so it must be O(1) there (the numpy recount
        # runs only on selection changes and recheck/resume)
        self._wanted_missing = self.info.num_pieces
        # paused: transfers suspended, connections and state kept alive
        self.paused = False
        from torrent_tpu.session.webseed import allowed_url as _ws_allowed

        # BEP 19 webseed URLs: the metainfo's url-list plus any added at
        # runtime (magnet ws= params arrive after construction). Both
        # sources are untrusted — only http/https survive. Under a SOCKS5
        # proxy, webseeds are refused wholesale (add_web_seed mirrors
        # this): their urllib fetches would dial around the tunnel.
        self.web_seed_urls: list[str] = (
            [] if proxy is not None
            else [u for u in metainfo.web_seeds if _ws_allowed(u)]
        )
        # BEP 17 httpseeds (piece-keyed GETs) ride the same loop with a
        # different fetcher; same untrusted-URL and proxy-leak guards
        self.http_seed_urls: list[str] = (
            [] if proxy is not None
            else [u for u in metainfo.http_seeds if _ws_allowed(u)]
        )
        if proxy is not None and (metainfo.web_seeds or metainfo.http_seeds):
            log.warning(
                "%d metainfo web/http seed(s) disabled: SOCKS5 proxy configured",
                len(metainfo.web_seeds) + len(metainfo.http_seeds),
            )
        # serve-path LRU of whole pieces (dict ordering = recency) and
        # in-flight reads shared by concurrent misses on the same piece
        self._serve_cache: dict[int, bytes] = {}
        self._serve_pending: dict[int, asyncio.Future] = {}
        self._rarity_dirty = True
        self._inflight_count: Counter = Counter()
        self._piece_inflight: Counter = Counter()  # per-piece mirror

        # Serialized info dict for BEP 9 metadata serving — byte-exact
        # re-encode of the decoded dict (decode preserves key order, so
        # sha1(info_bytes) == info_hash).
        self._info_bytes: bytes | None = None
        # BEP 52 merkle layer cache (hybrid torrents), built on first use
        self._hash_cache = _UNSET
        # outstanding layer fetches: request fields -> Future[hashes|None];
        # the lock serializes whole fetch_v2_layers runs (concurrent runs
        # would clobber each other's pending futures)
        self._hash_fetches: dict[tuple, asyncio.Future] = {}
        self._fetch_layers_lock = asyncio.Lock()

        # live announce counters (fixed vs torrent.ts:66-69 which never
        # updates them)
        self.uploaded = 0
        self.downloaded = 0
        # random per-session announce key (torrent.ts:62-74)
        self.key = random.randbytes(4)

        self.on_complete: asyncio.Event = asyncio.Event()

        # Swarm wire-plane observability (obs/swarm): the process-global
        # bounded per-peer telemetry registry, plus a deterministic
        # per-torrent trace id so connection lifecycle spans of one
        # swarm share one trace (`GET /v1/trace?id=swarm-<ih12>`).
        self._swarm_obs = swarm_telemetry()
        self._swarm_trace = f"swarm-{metainfo.info_hash.hex()[:12]}"
        # recv-stage accumulator (flushed in batches — see _recv_charge)
        self._recv_s = 0.0
        self._recv_bytes = 0
        self._recv_ops = 0

        # The crowd seeder plane (torrent_tpu/serve_plane/): bounded
        # reactor multiplexing peer request queues, zero-copy block
        # egress, and DRR choke economics — one set per torrent, all
        # feeding the process-global serve telemetry registry.
        from torrent_tpu.serve_plane.choke import ChokeEconomics
        from torrent_tpu.serve_plane.egress import EgressEngine
        from torrent_tpu.serve_plane.reactor import ReactorPool
        from torrent_tpu.serve_plane.telemetry import serve_telemetry

        self._serve_obs = serve_telemetry()
        self._egress = EgressEngine(storage, telemetry=self._serve_obs)
        self._serve_reactor = ReactorPool(
            self._reactor_serve,
            workers=self.config.serve_reactor_workers,
            per_peer_queue=self.config.serve_queue_depth,
            batch=self.config.serve_batch,
        )
        # deterministic per-torrent seed: the optimistic-slot rotation
        # replays identically for one info-hash (scenario discipline)
        self._serve_econ = ChokeEconomics(
            slots=self.config.unchoke_slots,
            quantum=self.config.choke_quantum,
            seed=int.from_bytes(metainfo.info_hash[:8], "big"),
        )
        # egress-stage ledger accumulator (flushed in batches, the
        # _recv_charge discipline — see _egress_charge)
        self._egress_s = 0.0
        self._egress_bytes = 0
        self._egress_ops = 0

    # ----------------------------------------------------------- lifecycle

    @property
    def private(self) -> bool:
        """BEP 27: the info dict's ``private`` flag (part of the infohash).

        Private torrents must not use DHT, PEX, or any peer source other
        than their own trackers.
        """
        info_raw = self.metainfo.raw.get(b"info")
        return isinstance(info_raw, dict) and info_raw.get(b"private") == 1

    @property
    def left(self) -> int:
        """Bytes still to download, counting only *wanted* pieces.

        One vectorized pass over the bool masks (a 100k-piece torrent is
        a 100 KB numpy op — no Python per-piece loop); with everything
        wanted (the default) this equals the whole-torrent remainder.
        """
        n = self.info.num_pieces
        if n == 0:
            return 0
        missing = (~self.bitfield.as_numpy()) & (self._piece_priority > 0)
        sizes = getattr(self.info, "piece_sizes", None)
        if sizes is not None:
            # v2 piece space: every file's last piece may be short
            return int(np.asarray(sizes)[missing].sum())
        left = int(missing.sum()) * self.info.piece_length
        if missing[n - 1]:
            left -= n * self.info.piece_length - self.info.length  # short tail
        return max(0, left)

    # ------------------------------------------------------ file selection

    def file_ranges(self) -> list[tuple[int, int]]:
        """Per-file ``(global_offset, length)`` spans, single- or multi-file."""
        if self.info.files is None:
            return [(0, self.info.length)]
        aligned = getattr(self.info, "piece_aligned", False)
        plen = self.info.piece_length
        out, pos = [], 0
        for fe in self.info.files:
            out.append((pos, fe.length))
            pos += -(-fe.length // plen) * plen if aligned else fe.length
        return out

    async def set_file_priorities(self, priorities: dict[int, int]) -> None:
        """Per-file download priorities: 0 = skip, higher = sooner.

        A piece overlapping any wanted file stays wanted (boundary pieces
        take the max priority of the files they touch — skipping them
        would corrupt the neighbouring wanted file). Files not named keep
        priority 1; BEP 47 pad entries are always priority 0 (their bytes
        are zeros — they must never keep a piece wanted on their own).
        Takes effect immediately: interest and pipelines are re-evaluated
        for every connected peer.
        """
        ranges = self.file_ranges()
        for idx, p in priorities.items():
            if not 0 <= idx < len(ranges):
                raise IndexError(f"no file #{idx} (torrent has {len(ranges)})")
            if not 0 <= int(p) <= 127:
                raise ValueError(f"priority {p} for file #{idx}: must be 0..127")
        # Serialized: the body suspends (partfile sweep in a thread), and
        # interleaved calls could otherwise leave the priority array from
        # one selection with the storage routing of another.
        async with self._selection_lock:
            await self._apply_file_priorities(priorities, ranges)

    async def _apply_file_priorities(self, priorities: dict[int, int], ranges) -> None:
        # the effective full mapping (unnamed files reset to 1 — this is
        # a whole-selection replacement API); BEP 39 apply_update reads
        # it to carry a selection across to the successor torrent
        self.file_priorities = {
            i: int(priorities.get(i, 1)) for i in range(len(ranges))
        }
        plen = self.info.piece_length
        entries = self.info.files or ()
        prio = np.zeros(self.info.num_pieces, dtype=np.int8)
        unwanted_files = set()
        for i, (start, length) in enumerate(ranges):
            if i < len(entries) and getattr(entries[i], "pad", False):
                continue  # pad spans never drive wanting (nor partfiles)
            p = int(priorities.get(i, 1))
            if p <= 0:
                unwanted_files.add(i)
            if length == 0 or p <= 0:
                continue
            first, last = start // plen, (start + length - 1) // plen
            np.maximum(prio[first : last + 1], p, out=prio[first : last + 1])
        self._piece_priority = prio
        # partfile routing: deselected files' boundary spill goes to the
        # hidden parts mirror; files (re-)entering the selection are
        # promoted back into place (no-op for memory backends). Off the
        # event loop: the promote sweep stats every file once.
        await asyncio.to_thread(self.storage.set_unwanted_files, unwanted_files)
        # a new selection invalidates the boost snapshot; active reader
        # windows re-apply over the new mask, and parked readers re-check
        # (a newly-deselected piece must raise, not hang)
        self._stream_base = None
        if self._stream_positions:
            self._apply_stream_windows()
        self._wake_all_waiters()
        self._recount_wanted()
        self._rarity_dirty = True
        if (
            self.state == TorrentState.SEEDING
            and self._wanted_remaining()
            and not self._stopping
        ):
            # widening a satisfied selection re-opens the download: the
            # completion latch resets, the webseed loops (which exit when
            # nothing is wanted) are respawned, and the announce loop is
            # woken — a peerless torrent must not sit out a full tracker
            # interval before discovering anyone to fetch from
            self.state = TorrentState.DOWNLOADING
            self.on_complete.clear()
            self._spawn_seed_loops()
            self.request_peers()
        for peer in list(self.peers.values()):
            try:
                await self._update_interest(peer)
            except (ConnectionError, OSError):
                pass
        await self._maybe_completed()

    async def select_files(self, wanted: list[int]) -> None:
        """Download only the named file indices (sugar over priorities)."""
        ranges = self.file_ranges()
        want = set(wanted)
        unknown = want - set(range(len(ranges)))
        if unknown:
            raise IndexError(
                f"no file #{min(unknown)} (torrent has {len(ranges)})"
            )
        await self.set_file_priorities(
            {i: (1 if i in want else 0) for i in range(len(ranges))}
        )

    # ------------------------------------------------------------ streaming

    def _notify_piece(self, index: int) -> None:
        ev = self._piece_events.pop(index, None)
        if ev is not None:
            ev.set()

    def _notify_present_pieces(self) -> None:
        """Wake waiters after a BULK bitfield update (recheck adopting a
        fresh array, fastresume replacing it wholesale) — per-piece
        completion goes through _finish_piece → _notify_piece."""
        for index in [i for i in self._piece_events if self.bitfield.has(i)]:
            self._notify_piece(index)

    async def wait_piece(self, index: int) -> None:
        """Block until piece ``index`` is verified on disk (streaming
        readers park here while the scheduler fetches ahead of them).

        Raises instead of parking forever when the piece became
        unreachable: RuntimeError once the torrent is stopping,
        LookupError when the piece is deselected (priority 0) — both
        re-checked every wake, and stop()/set_file_priorities wake all
        parked waiters precisely so these fire."""
        if not 0 <= index < self.info.num_pieces:
            raise IndexError(f"piece {index} out of range")
        while not self.bitfield.has(index):
            if self._stopping:
                raise RuntimeError("torrent stopped while waiting for a piece")
            if self._piece_priority[index] <= 0:
                raise LookupError(f"piece {index} is not scheduled (deselected)")
            ev = self._piece_events.get(index)
            if ev is None:
                ev = self._piece_events[index] = asyncio.Event()
            await ev.wait()

    def _wake_all_waiters(self) -> None:
        """Set (and drop) every parked piece event so waiters re-check
        their abort conditions — completion still only comes from the
        bitfield check in wait_piece's loop."""
        events = list(self._piece_events.values())
        self._piece_events.clear()
        for ev in events:
            ev.set()

    def span_servable(self, start: int, length: int) -> bool:
        """True when every piece of byte span [start, start+length) is
        on disk already or wanted (priority > 0) — the condition under
        which a stream reader is guaranteed to eventually be served."""
        if length <= 0:
            return False
        plen = self.info.piece_length
        first, last = start // plen, (start + length - 1) // plen
        base = self._stream_base if self._stream_base is not None else self._piece_priority
        missing = ~self.bitfield.as_numpy()[first : last + 1]
        return not bool(np.any(missing & (base[first : last + 1] <= 0)))

    def set_stream_window(
        self, offset: int, window_pieces: int = 8, token: object = "default"
    ) -> None:
        """Point the scheduler at a reader position: the next
        ``window_pieces`` pieces from ``offset`` (including any already
        on disk — the window is positional) jump to maximum priority
        (127), and pieces the reader moved past fall back to their
        pre-boost priority. Random seeks (HTTP Range requests) re-point
        the window instantly; deselected (priority-0) pieces are never
        boosted — streaming doesn't widen the selection.

        ``token`` names the reader: concurrent readers (players open a
        head and a tail connection at once) each hold a window and the
        boost is their union, so one reader's chunk cadence can't wipe
        the other's read-ahead. No-op when the token's window start
        hasn't moved (the array rewrite is O(pieces)).
        """
        plen = self.info.piece_length
        first = min(max(0, offset // plen), self.info.num_pieces - 1)
        prev = self._stream_positions.get(token)
        if prev == (first, window_pieces):
            return
        self._stream_positions[token] = (first, window_pieces)
        if self._stream_base is None or prev is None:
            self._apply_stream_windows()
            return
        # Steady-state window advance: O(window) delta — restore pieces
        # the window left (unless another reader still covers them),
        # boost the newly-entered ones. No rarity rebuild: the picker
        # consults stream windows directly, so priority-array lag only
        # affects the (eventual) background ordering.
        old = set(range(prev[0], min(prev[0] + prev[1], self.info.num_pieces)))
        new = set(range(first, min(first + window_pieces, self.info.num_pieces)))
        still = set()
        for f, n in self._stream_positions.values():
            still.update(range(f, min(f + n, self.info.num_pieces)))
        for i in old - new - still:
            self._piece_priority[i] = self._stream_base[i]
        for i in new - old:
            if self._stream_base[i] > 0:
                self._piece_priority[i] = np.int8(127)

    def clear_stream_window(self, token: object = None) -> None:
        """Drop one reader's window (``token``) or, with None, all."""
        if token is None:
            if not self._stream_positions:
                return
            self._stream_positions.clear()
        elif self._stream_positions.pop(token, None) is None:
            return
        self._apply_stream_windows()

    def _apply_stream_windows(self) -> None:
        """Full restore + reapply (token add/remove, selection change) —
        window ADVANCES take the O(window) delta path in
        set_stream_window instead."""
        if self._stream_base is None:
            self._stream_base = self._piece_priority.copy()
        else:
            np.copyto(self._piece_priority, self._stream_base)
        for first, window_pieces in self._stream_positions.values():
            window = self._piece_priority[first : first + window_pieces]
            np.copyto(window, np.where(window > 0, np.int8(127), window))
        if not self._stream_positions:
            self._stream_base = None
        self._rarity_dirty = True

    def _wanted_remaining(self) -> int:
        """Count of wanted pieces not yet verified on disk (cached)."""
        return self._wanted_missing

    def _recount_wanted(self) -> None:
        prev = getattr(self, "_wanted_missing", 0)
        self._wanted_missing = int(
            ((~self.bitfield.as_numpy()) & (self._piece_priority > 0)).sum()
        )
        if (
            self._endgame
            and self._wanted_missing > prev
            and self._wanted_missing > self._tail_threshold()
        ):
            # wants GREW mid-endgame (piece lost, selection widened):
            # this is no longer a tail — duplication would flood.
            # Outstanding duplicates still cancel on arrival: the cancel
            # broadcast keys on remaining in-flight copies, not on the
            # endgame flag.
            self._endgame = False

    def _tail_threshold(self) -> int:
        """Wanted-piece count at or below which endgame duplication is
        worth its cancel traffic — shared by the entry (_fill_pipeline)
        and exit (_recount_wanted) gates so they cannot drift apart and
        flap."""
        return max(8, 2 * len(self.peers))

    async def start(self) -> None:
        """Resume from checkpoint or recheck existing data, then join."""
        self.state = TorrentState.CHECKING
        if not self._try_fastresume():
            await self.recheck()
        self.state = TorrentState.SEEDING if self.bitfield.complete else TorrentState.DOWNLOADING
        if self.bitfield.complete:
            self.on_complete.set()
            # already complete at start: either a prior session sent the
            # tracker its `completed` or this was never a download at all
            # — a later piece-loss/re-fetch cycle must not send one
            self._completed_reported = True
        self._stopping = False
        if self.trackers:
            self._spawn(self._announce_loop(), name="announce")
        # BEP 27: a private torrent's peers come from its trackers ONLY —
        # no DHT announces, no PEX gossip (tools/make_torrent.py writes
        # the flag; without this gate the session would leak the swarm).
        if self.dht is not None and not self.private:
            self._spawn(self._dht_loop(), name="dht")
        self._spawn(self._choke_loop(), name="choke")
        self._spawn(self._keepalive_loop(), name="keepalive")
        self._spawn(self._idle_sweep_loop(), name="idle-sweep")
        # the serve reactor: inbound Requests queue per peer and a
        # bounded worker pool drains them (serve_plane/reactor.py);
        # workers ride _spawn so stop() tears them down with the rest
        self._serve_reactor.start(self._spawn)
        if not self.private:
            self._spawn(self._pex_loop(), name="pex")
        self._spawn_seed_loops()

    def add_web_seed(self, url: str) -> bool:
        """Attach a BEP 19 webseed at runtime (e.g. a magnet's ``ws=``).

        Deduplicated and scheme-checked (untrusted input: only http/https
        — urllib would happily open file:// or ftp://); if the torrent is
        already running and pieces are still wanted, the fetch loop
        starts immediately. True when the URL was newly attached."""
        from torrent_tpu.session.webseed import allowed_url

        if self.proxy is not None:
            # webseed fetches ride urllib, which would dial AROUND the
            # configured proxy — refuse rather than leak the client's
            # address to the webseed host
            log.warning("webseed %s disabled: SOCKS5 proxy configured", url)
            return False
        if url in self.web_seed_urls or not allowed_url(url):
            return False
        self.web_seed_urls.append(url)
        if self.state in (TorrentState.DOWNLOADING, TorrentState.SEEDING):
            self._spawn(self._webseed_loop(url), name=f"webseed-{url[:24]}")
        return True

    def _spawn(self, coro, name=None) -> asyncio.Task:
        """Track a task for teardown; completed tasks self-evict."""
        task = asyncio.create_task(coro, name=name)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    def _try_fastresume(self) -> bool:
        """Load a fastresume checkpoint; False → caller runs full recheck.

        Claimed pieces are sanity-checked against file existence (not
        content — that's what ``recheck`` is for; a stale checkpoint at
        worst serves bad pieces which peers' own verification rejects).
        """
        if self.resume_store is None:
            return False
        rd = self.resume_store.load(self.metainfo.info_hash)
        if rd is None or rd.num_pieces != self.info.num_pieces:
            return False
        try:
            bf = Bitfield(self.info.num_pieces, rd.bitfield)
        except ValueError:
            return False
        if bf.count() > 0:
            # each claimed piece's files must exist AND reach the extent
            # that piece needs — a crash-truncated file fails here and
            # falls back to the full recheck
            needed_extent: dict[tuple, int] = {}
            for i in range(self.info.num_pieces):
                if bf.has(i):
                    for path, foff, chunk in self.storage.segments(
                        i * self.info.piece_length, piece_length(self.info, i)
                    ):
                        if path is None:
                            continue  # BEP 47 pad span: nothing on disk
                        needed_extent[path] = max(needed_extent.get(path, 0), foff + chunk)
            if not all(
                self.storage.method.exists(p, length)
                for p, length in needed_extent.items()
            ):
                return False
        self.bitfield = bf
        self._notify_present_pieces()
        self._recount_wanted()
        self._rarity_dirty = True
        # Re-ingest checkpointed in-flight pieces: the scheduler resumes
        # mid-piece instead of re-downloading up to piece_length per
        # partial. The data is untrusted-by-construction — verification
        # still gates persistence when the piece completes, exactly as
        # for wire blocks.
        for index, (mask, data) in (rd.partials or {}).items():
            if (
                not isinstance(index, int)
                or not 0 <= index < self.info.num_pieces
                or bf.has(index)
                or index in self._partials
            ):
                continue
            plen_i = piece_length(self.info, index)
            if len(data) != plen_i:
                continue  # geometry changed or corrupt: drop the partial
            received = set()
            for b in range((plen_i + BLOCK_SIZE - 1) // BLOCK_SIZE):
                if b // 8 < len(mask) and mask[b // 8] & (1 << (b % 8)):
                    received.add(b * BLOCK_SIZE)
            if not received:
                continue
            partial = _PartialPiece(
                index=index,
                length=plen_i,
                buffer=bytearray(data),
                received=received,
            )
            if partial.complete:
                # defense against old/foreign checkpoints: a complete
                # partial has no missing block to trigger _finish_piece —
                # drop it and let the scheduler re-fetch the piece
                continue
            self._partials[index] = partial
            # periodic checkpoints keep carrying this partial until the
            # piece completes (an unclean death must not lose it)
            self._saved_partials[index] = (mask, data)
        self.storage.mark_pieces_written(
            i for i in range(self.info.num_pieces) if bf.has(i)
        )
        self.uploaded = rd.uploaded
        self.downloaded = rd.downloaded
        # a restart mid-heal (incomplete bitfield) must still remember
        # that `completed` already went to the tracker — and a crash
        # between queuing the event and the announce leaves it owed
        self._completed_reported = self._completed_reported or rd.completed_reported
        self._pending_completed = self._pending_completed or rd.completed_owed
        log.info("fastresume: %d/%d pieces", bf.count(), self.info.num_pieces)
        return True

    def _checkpoint(self, include_partials: bool = False) -> None:
        if self.resume_store is None:
            return
        from torrent_tpu.session.resume import ResumeData

        # Partial buffers ride only the STOP-time checkpoint: serializing
        # up to piece_length per in-flight piece inside the periodic
        # 16-piece checkpoint would do megabytes of copy+bencode+write on
        # the event loop mid-download. Entry-count capping happens once,
        # in ResumeData.encode.
        if include_partials:
            partials = {}
            for index, p in list(self._partials.items()):
                if not p.received or p.complete:
                    # empty webseed reservations carry nothing; COMPLETE
                    # partials must never persist — a re-ingested complete
                    # partial has no missing block to trigger
                    # _finish_piece and would stall the download forever
                    continue
                n_blocks = (len(p.buffer) + BLOCK_SIZE - 1) // BLOCK_SIZE
                mask = bytearray((n_blocks + 7) // 8)
                for begin in p.received:
                    b = begin // BLOCK_SIZE
                    mask[b // 8] |= 1 << (b % 8)
                partials[index] = (bytes(mask), bytes(p.buffer))
            self._saved_partials = partials
        else:
            # the periodic checkpoint carries FORWARD previously saved
            # partials (already-serialized bytes, no buffer copying) for
            # pieces still incomplete — an unclean death between a
            # resume and the next stop must not lose them. Re-assigning
            # the filtered dict also releases completed pieces' buffers
            # instead of pinning them in RAM for the session's lifetime.
            partials = {
                i: sp
                for i, sp in self._saved_partials.items()
                if not self.bitfield.has(i)
            }
            self._saved_partials = partials
        try:
            self.resume_store.save(
                ResumeData(
                    info_hash=self.metainfo.info_hash,
                    num_pieces=self.info.num_pieces,
                    bitfield=self.bitfield.to_bytes(),
                    uploaded=self.uploaded,
                    downloaded=self.downloaded,
                    partials=partials,
                    completed_reported=self._completed_reported,
                    completed_owed=self._pending_completed,
                )
            )
        except OSError as e:
            log.warning("checkpoint save failed: %s", e)

    async def recheck(self) -> None:
        """Rebuild the bitfield by hashing what's on disk (resume path)."""
        from torrent_tpu.parallel.verify import verify_pieces

        if not any(
            self.storage.method.exists(path)
            for path, _, _ in self.storage._files
            if path is not None  # pads never exist on disk
        ):
            return  # nothing on disk, skip the scan
        cfg = self.config
        if cfg.scheduler is not None and not getattr(self.info, "v2", False):
            # shared-plane path: submit to the process-wide verify queue
            # as a low-priority tenant — the scheduler coalesces these
            # pieces with foreground traffic and its DRR keeps the
            # background recheck from starving anyone (and vice versa:
            # low weight, never zero, so it always progresses)
            from torrent_tpu.parallel.verify import verify_pieces_sched
            from torrent_tpu.sched import SchedRejected

            cfg.scheduler.register_tenant("selfheal", weight=cfg.selfheal_weight)
            try:
                # per-piece launch failures come back as unverified
                # (False) inside verify_pieces_sched — only a whole-
                # queue rejection (scheduler shutting down) falls
                # through to the local verify path below
                ok = await verify_pieces_sched(
                    self.storage, self.info, cfg.scheduler, tenant="selfheal"
                )
                self._apply_recheck(ok)
                return
            except SchedRejected as e:
                log.warning("scheduler recheck rejected (%s); local fallback", e)
        kwargs = {}
        if cfg.hasher == "tpu":
            kwargs = {"batch_size": cfg.verify_batch_size}
            if self.verifier is not None:
                ok = await asyncio.to_thread(
                    self.verifier.verify_storage, self.storage, self.info
                )
                self._apply_recheck(ok)
                return
        ok = await asyncio.to_thread(
            verify_pieces, self.storage, self.info, cfg.hasher, None, **kwargs
        )
        self._apply_recheck(ok)

    def _apply_recheck(self, ok) -> None:
        self.bitfield.from_numpy(ok)
        self._notify_present_pieces()
        self._recount_wanted()
        self.storage.mark_pieces_written(i for i in range(len(ok)) if ok[i])
        log.info(
            "recheck: %d/%d pieces valid", self.bitfield.count(), self.info.num_pieces
        )

    async def stop(self) -> None:
        self._stopping = True
        self._wake_all_waiters()  # parked stream readers abort, not hang
        self._serve_reactor.forget()  # workers die with _tasks below
        tasks = list(self._tasks)
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        for peer in list(self.peers.values()):
            self._swarm_obs.peer_dropped(self._obs_key(peer))
            peer.close()
        self.peers.clear()
        self._recv_flush()  # residual wire charges reach the ledger
        self._egress_flush()  # and residual serve charges with them
        self._checkpoint(include_partials=True)  # stop: keep in-flight work
        if self.trackers:
            try:
                await asyncio.wait_for(
                    self.trackers.announce(self._announce_info(AnnounceEvent.STOPPED)),
                    timeout=5,
                )
            except Exception:
                pass  # best-effort goodbye
        self.state = TorrentState.STOPPED

    # ------------------------------------------------------------ announce

    def _announce_info(self, event: AnnounceEvent) -> AnnounceInfo:
        return AnnounceInfo(
            info_hash=self.metainfo.info_hash,
            peer_id=self.peer_id,
            port=self.port,
            uploaded=self.uploaded,
            downloaded=self.downloaded,
            left=self.left,
            event=event,
            num_want=DEFAULT_NUM_WANT if len(self.peers) < self.config.max_peers else 0,
            key=self.key,
        )

    async def _announce_loop(self) -> None:
        """(torrent.ts:224-244) with early wake via request_peers()."""
        started_sent = False
        while not self._stopping:
            if not started_sent:
                event = AnnounceEvent.STARTED
            elif self._pending_completed:
                event = AnnounceEvent.COMPLETED  # report the snatch (BEP 3)
            else:
                event = AnnounceEvent.EMPTY
            interval = self.config.announce_retry
            try:
                res = await self.trackers.announce(self._announce_info(event))
                self._swarm_obs.on_announce(True, origin=self._swarm_trace)
                if event == AnnounceEvent.STARTED:
                    started_sent = True
                elif event == AnnounceEvent.COMPLETED:
                    self._pending_completed = False
                    # persist delivery NOW: dying before the next periodic
                    # checkpoint would leave `completed` owed on disk and
                    # the restarted session would announce it twice
                    self._checkpoint()
                interval = max(5, res.interval)
                if res.external_ip:
                    # BEP 24: learn our public address from the tracker —
                    # this is what makes BEP 40 dial ordering live without
                    # UPnP (the common NAT'd configuration). Only global
                    # addresses are trusted: dial ordering is a soft
                    # preference, and a hostile tracker shouldn't get to
                    # skew it with loopback/multicast/reserved junk.
                    import ipaddress

                    try:
                        if ipaddress.ip_address(res.external_ip).is_global:
                            self.external_ip = res.external_ip
                    except ValueError:
                        pass
                self._connect_new_peers(res.peers)
            except TrackerError as e:
                log.warning("announce failed: %s", e)
                # failure-streak telemetry: ANNOUNCE_STREAK consecutive
                # failures fire one flight dump (the swarm is coasting
                # on cached peers), re-armed by the next success
                self._swarm_obs.on_announce(False, origin=self._swarm_trace)
            except Exception as e:
                log.warning("announce error: %s", e)
                self._swarm_obs.on_announce(False, origin=self._swarm_trace)
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=interval)
            except asyncio.TimeoutError:
                pass

    def request_peers(self) -> None:
        """Early announce wake (torrent.ts:104-107)."""
        self._wake.set()

    # ------------------------------------------------------------- pausing

    async def pause(self) -> None:
        """Suspend transfers without tearing the session down.

        Connections stay up (cheap to resume; availability intact) but:
        outstanding requests are cancelled and released, no new requests
        or serves happen, and peers are choked. The announce loop keeps
        its interval (trackers still see us; BEP 21-style 'paused' is
        not a wire concept in BEP 3).
        """
        if self.paused:
            return
        self.paused = True
        for p in list(self.peers.values()):
            await self._cancel_and_release(p)
            if not p.am_choking:
                p.am_choking = True
                self._swarm_obs.on_state(self._obs_key(p), am_choking=True)
                try:
                    await proto.send_message(p.writer, proto.Choke())
                except (ConnectionError, OSError):
                    pass

    async def resume(self) -> None:
        """Undo ``pause``: refill pipelines; the choke loop re-unchokes."""
        if not self.paused:
            return
        self.paused = False
        for p in list(self.peers.values()):
            if p.am_interested and not p.peer_choking:
                try:
                    await self._fill_pipeline(p)
                except (ConnectionError, OSError):
                    pass
        self.request_peers()

    async def _dht_loop(self) -> None:
        """BEP 5: announce our port and pull swarm peers from the DHT.

        Runs alongside (or instead of — trackerless magnets) the tracker
        announce loop.
        """
        from torrent_tpu.net.types import AnnouncePeer

        ih = self.metainfo.info_hash
        while not self._stopping:
            try:
                # BEP 33: advertise completion so DHT scrapers can count
                # seeds vs downloaders
                await self.dht.announce(ih, self.port, seed=self.bitfield.complete)
                if self.state != TorrentState.SEEDING:
                    peers = await self.dht.lookup_peers(ih)
                    self._connect_new_peers(
                        [AnnouncePeer(ip=h, port=p) for h, p in peers]
                    )
            except Exception as e:
                log.debug("dht round failed: %s", e)
            await asyncio.sleep(self.config.dht_interval)

    # ------------------------------------------------------------- dialing

    def _connect_new_peers(self, candidates) -> None:
        """Outbound dials, deduped and capped (fixes SURVEY §8.14).

        With a known external address, candidates are dialed in BEP 40
        canonical-priority order (net/priority.py) — both swarm ends
        derive the same ranking, converging the neighbor graph.
        """
        if self.state == TorrentState.SEEDING:
            return  # seeds serve inbound connections; nothing to fetch
        if self.external_ip:
            from torrent_tpu.net.priority import peer_priority

            me = (self.external_ip, self.port)
            candidates = sorted(
                candidates,
                key=lambda c: peer_priority(me, (c.ip, c.port)),
                reverse=True,
            )
        connected = {p.address for p in self.peers.values() if p.address}
        for cand in candidates:
            if len(self.peers) + len(self._dialing) >= self.config.max_peers:
                break
            addr = (cand.ip, cand.port)
            if addr in connected or addr in self._dialing:
                continue
            if cand.ip in self._banned:
                continue
            if self.ip_filter is not None and self.ip_filter.blocked(cand.ip):
                continue
            if cand.peer_id == self.peer_id:
                continue
            self._dialing.add(addr)
            self._spawn(self._dial(addr, cand.peer_id))

    async def _open_transport(self, addr: tuple[str, int]):
        """Connect a transport to ``addr``; returns streams or (None, None).

        With uTP enabled (BEP 29) the dial races uTP against TCP,
        happy-eyeballs style: uTP gets a short head start (it is the
        transport most swarms prefer), TCP starts 250 ms later, first
        connected stream wins and the loser is torn down. A TCP-only
        peer therefore costs ~250 ms extra, not a full uTP timeout —
        ICMP unreachable for UDP is not surfaced per-address by asyncio,
        so a sequential uTP-then-TCP dial would stall every TCP-only
        connection for seconds.
        """
        reader = writer = None
        if self._utp_dial is not None:
            utp_task = asyncio.ensure_future(
                self._utp_dial(addr[0], addr[1], timeout=8)
            )

            async def tcp_late():
                await asyncio.sleep(0.25)
                return await asyncio.open_connection(addr[0], addr[1])

            tcp_task = asyncio.ensure_future(tcp_late())
            pending = {utp_task, tcp_task}
            try:
                end = time.monotonic() + 10
                while pending and reader is None:
                    done, pending = await asyncio.wait(
                        pending,
                        timeout=max(0, end - time.monotonic()),
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                    if not done:
                        break  # overall timeout
                    for t in done:
                        if t.exception() is None and reader is None:
                            reader, writer = t.result()
            finally:
                for t in pending:
                    t.cancel()
                for t in (utp_task, tcp_task):
                    if t.done() and not t.cancelled() and t.exception() is None:
                        r, w = t.result()
                        if w is not writer:
                            w.close()  # the losing transport
        else:
            try:
                if self.proxy is not None:
                    from torrent_tpu.net.socks import open_connection as socks_open

                    reader, writer = await asyncio.wait_for(
                        socks_open(self.proxy, addr[0], addr[1]), timeout=20
                    )
                else:
                    reader, writer = await asyncio.wait_for(
                        asyncio.open_connection(addr[0], addr[1]), timeout=10
                    )
            except (OSError, asyncio.TimeoutError):
                reader = writer = None
        return reader, writer

    async def _dial(self, addr: tuple[str, int], expect_peer_id: bytes | None) -> None:
        """connect/handshake/verify/register (torrent.ts:198-222).

        MSE/PE (net/mse.py): 'enabled' dials plaintext first and retries
        the whole connection encrypted when the plaintext handshake is
        refused (an encryption-requiring peer drops it on sight);
        'required' dials encrypted only.
        """
        from torrent_tpu.net import mse

        class _TerminalDial(Exception):
            """Handshake completed and was rejected on its merits (wrong
            infohash, self-connect) — retrying encrypted proves nothing."""

        policy = self.config.encryption
        modes = {
            "disabled": ("plain",),
            "enabled": ("plain", "mse"),
            "required": ("mse",),
        }[policy]
        pid = reserved = None
        try:
            for mode in modes:
                reader, writer = await self._open_transport(addr)
                if reader is None:
                    return
                try:
                    if mode == "mse":
                        reader, writer, _sel = await asyncio.wait_for(
                            mse.initiate(
                                reader,
                                writer,
                                self.metainfo.info_hash,
                                allow_plaintext=policy != "required",
                            ),
                            timeout=15,
                        )
                    await proto.send_handshake(
                        writer,
                        self.metainfo.info_hash,
                        self.peer_id,
                        proto.merge_reserved(
                            ext.extension_reserved(), proto.fast_reserved()
                        ),
                    )
                    ih, reserved = await asyncio.wait_for(
                        proto.read_handshake_head(reader), timeout=10
                    )
                    pid = await asyncio.wait_for(
                        proto.read_handshake_peer_id(reader), timeout=10
                    )
                    if ih != self.metainfo.info_hash or (
                        expect_peer_id and pid != expect_peer_id
                    ):
                        raise _TerminalDial("handshake mismatch")
                    if pid == self.peer_id:
                        raise _TerminalDial("connected to self")
                    break  # handshake complete on this mode
                except _TerminalDial:
                    writer.close()
                    return
                except (
                    mse.MseError,
                    proto.ProtocolError,
                    asyncio.TimeoutError,
                    asyncio.IncompleteReadError,
                    OSError,
                ):
                    writer.close()
                    pid = None
            if pid is None:
                return
        finally:
            self._dialing.discard(addr)
        await self.add_peer(pid, reader, writer, address=addr, reserved=reserved)

    # ------------------------------------------------------------ peer mgmt

    def _evictable_peer(self):
        """Pick a peer whose slot can be recycled for a fresh
        connection: mutually uninterested, nothing in flight either
        way, past the interest grace period (``config.evict_grace``) —
        longest-idle first. None when every slot is doing (or may yet
        do) something."""
        now = time.monotonic()
        best = None
        for p in self.peers.values():
            if p.peer_interested or p.am_interested or p.inflight:
                continue
            if now - p.connected_at < self.config.evict_grace:
                continue
            if best is None or p.last_rx < best.last_rx:
                best = p
        return best

    async def add_peer(
        self,
        peer_id,
        reader,
        writer,
        address=None,
        reserved: bytes = b"\x00" * 8,
        inbound: bool = False,
    ) -> None:
        """Register + spawn the message loop (torrent.ts:79-102)."""
        existing = self.peers.get(peer_id)
        if existing is not None:
            if existing.inbound == inbound:
                # True reconnect: keep the established connection, close
                # the duplicate — the reference overwrote the map entry
                # and leaked the old socket (§8.14). Stale survivors die
                # via the peer timeout.
                writer.close()
                return
            # Simultaneous open (each end dialed the other — the BEP 55
            # holepunch MAKES this happen on purpose): both ends must
            # keep the SAME connection or the cross-closes kill both.
            # Deterministic tie-break: the connection initiated by the
            # numerically smaller peer id survives on both sides.
            new_initiated_by_us = not inbound
            smaller_is_us = self.peer_id < peer_id
            if new_initiated_by_us != smaller_is_us:
                writer.close()  # the agreed loser
                return
            self._drop_peer(existing)  # replaced by the agreed survivor
        if len(self.peers) >= self.config.max_peers:
            # Slot recycling: a full peer list must not be a permanent
            # wall. A swarm larger than max_peers otherwise starves —
            # peers that already got what they wanted (not interested,
            # nothing in flight either way) sit on their slot forever
            # and the excess peers are refused on every retry (observed:
            # an 80-leech disjoint-selection soak plateaued at exactly
            # 50 leeches' worth of pieces). Real clients evict an idle
            # uninterested peer to admit a fresh one; so do we.
            victim = self._evictable_peer()
            if victim is None:
                writer.close()
                return
            log.debug(
                "peer list full — recycling idle slot %r", victim.peer_id[:8]
            )
            self._drop_peer(victim)
        if address and address[0] in self._banned:
            writer.close()  # banned peers don't get back in by reconnecting
            return
        if address and self.ip_filter is not None and self.ip_filter.blocked(address[0]):
            writer.close()  # blocklisted ranges are refused inbound too
            return
        # the AcceptGate is the front door: slot admission + the per-IP
        # clamp (a one-address stampede is turned away HERE, before a
        # PeerConnection or a peer loop exists for it)
        if not self._accept_gate.connect(
            peer_id, time.monotonic(), ip=address[0] if address else None
        ):
            self._serve_obs.on_reject(
                self._gate_key(peer_id, address),
                self._accept_gate.last_reject or "capacity",
            )
            writer.close()
            return
        peer = PeerConnection(
            peer_id=peer_id,
            reader=reader,
            writer=writer,
            num_pieces=self.info.num_pieces,
            address=address,
            inbound=inbound,
        )
        peer.ext.enabled = ext.supports_extensions(reserved)
        peer.fast = proto.supports_fast(reserved)
        self.peers[peer_id] = peer
        # serialize frame sends: zero-copy egress holds this lock across
        # header + sendfile (asyncio forbids transport.write while a
        # sendfile is in flight), and proto.send_message honors it
        try:
            writer._tt_send_lock = asyncio.Lock()
        except AttributeError:
            pass  # slotted writer fakes: no sendfile path for them anyway
        # connection lifecycle telemetry + tracer span (obs/swarm): one
        # deterministic trace per torrent collects connect/drop spans
        self._swarm_obs.peer_connected(
            self._obs_key(peer), inbound=inbound, trace_id=self._swarm_trace
        )
        # Opening state message. BEP 6 peers get the compact have_all /
        # have_none forms; everyone else gets the raw bitfield
        # (protocol.ts:108-115 sends the bitfield unconditionally).
        # Super-seeding (BEP 16) hides everything and reveals pieces
        # one-by-one via the targeted Haves granted below.
        if self.super_seeding():
            if peer.fast:
                writer.write(proto.encode_message(proto.HaveNone()))
            else:
                proto.send_bitfield(writer, Bitfield(self.info.num_pieces))
        elif peer.fast and self.bitfield.complete:
            writer.write(proto.encode_message(proto.HaveAll()))
        elif peer.fast and self.bitfield.count() == 0:
            writer.write(proto.encode_message(proto.HaveNone()))
        else:
            proto.send_bitfield(writer, self.bitfield)
        if not self.super_seeding():
            # this peer sees our real piece map now — if BEP 16 turns on
            # later (runtime toggle, or a super_seed-configured download
            # completing), the serve gate must not refuse it
            peer.ss_exempt = True
        if peer.fast and address is not None and not self.super_seeding():
            # Canonical allowed-fast grants (both ends can derive the same
            # set, so grants survive reconnects). Served while choked only
            # for pieces we actually have; the rest get explicit rejects.
            for i in proto.allowed_fast_set(
                address[0], self.metainfo.info_hash, self.info.num_pieces
            ):
                peer.allowed_fast_out.add(i)
                writer.write(proto.encode_message(proto.AllowedFast(i)))
        if peer.ext.enabled:
            # BEP 10: extended handshake right after the bitfield,
            # advertising ut_metadata (magnet joiners fetch the info dict
            # from us) and our listen port (so PEX about us is dialable).
            writer.write(
                proto.encode_message(
                    proto.Extended(
                        0,
                        ext.encode_extended_handshake(
                            len(self.info_bytes()),
                            listen_port=self.port,
                            # BEP 27: no off-tracker peer sources — that
                            # rules out holepunch introductions too
                            exclude=(ext.UT_PEX, ext.UT_HOLEPUNCH)
                            if self.private
                            else (),
                        ),
                    )
                )
            )
        if self.super_seeding():
            # initial BEP 16 grants: reveal the first pieces to this peer
            for q in self._ss_pick(peer):
                writer.write(proto.encode_message(proto.Have(index=q)))
        peer.snapshot_rate()
        self._spawn(self._peer_loop(peer), name=f"peer-{peer_id[:8].hex()}")

    def _drop_peer(self, peer: PeerConnection) -> None:
        """Teardown on loop exit (torrent.ts:88-99) + reschedule its blocks.

        Idempotent: the ban path and the peer loop's finally can both call
        this; availability must only be decremented once.
        """
        peer.close()
        if self.peers.get(peer.peer_id) is not peer:
            return  # already dropped (or replaced by a newer connection)
        del self.peers[peer.peer_id]
        self._accept_gate.release(peer.peer_id)
        self._serve_reactor.drop(peer.peer_id)  # queued requests die too
        self._swarm_obs.peer_dropped(self._obs_key(peer))
        self._serve_obs.peer_gone(self._obs_key(peer))
        self._recv_flush()  # a departing peer must not strand recv charges
        self._avail -= peer.bitfield.as_numpy()
        self._rarity_dirty = True
        if self._ss_assigned is not None:
            # unconfirmed BEP 16 grants return to the pool so the next
            # peer can be offered them (least-granted-first picks them up)
            for q in peer.ss_unconfirmed:
                self._ss_assigned[q] -= 1
            peer.ss_unconfirmed.clear()
        self._release_inflight(peer)

    def _inflight_add(self, blk) -> None:
        if self._inflight_count[blk] == 0:
            # the mirror counts DISTINCT requested blocks per piece (not
            # request multiplicity): endgame duplication must not inflate
            # it, or the picker's saturation skip would starve a piece
            # with one duplicated and one unrequested block
            self._piece_inflight[blk[0]] += 1
        self._inflight_count[blk] += 1

    def _inflight_release(self, blk) -> None:
        if self._inflight_count[blk] > 0:
            self._inflight_count[blk] -= 1
            if self._inflight_count[blk] == 0:
                self._piece_inflight[blk[0]] -= 1

    def _release_inflight(self, peer: PeerConnection) -> None:
        for blk in peer.inflight:
            self._inflight_release(blk)
        peer.inflight.clear()
        peer.inflight_choked.clear()
        peer.req_sent_at.clear()

    async def _cancel_and_release(self, peer: PeerConnection) -> None:
        """Cancel every outstanding request to ``peer`` on the wire and
        release the blocks for other peers (pause + snub sweep share
        this; a dead writer just stops the cancels — release happens
        regardless)."""
        for blk in list(peer.inflight):
            try:
                await proto.send_message(peer.writer, proto.Cancel(*blk))
            except (ConnectionError, OSError):
                break
        self._release_inflight(peer)

    async def _replace_bitfield(self, peer: PeerConnection, new_bf: Bitfield) -> None:
        """Swap a peer's piece map (bitfield / have_all / have_none),
        keeping the availability vector and interest state consistent."""
        # in-place ufuncs cast bool→int32 themselves; no copies
        self._avail += new_bf.as_numpy()
        self._avail -= peer.bitfield.as_numpy()
        peer.bitfield = new_bf
        self._rarity_dirty = True
        if self.super_seeding() and peer.ss_unconfirmed:
            # grants the peer turns out to already have can never be
            # confirmed by its uploads — return them and re-grant
            stale = [q for q in peer.ss_unconfirmed if new_bf.has(q)]
            for q in stale:
                peer.ss_unconfirmed.discard(q)
                self._ss_assigned[q] -= 1
            if stale:
                await self._ss_grant(peer)
        await self._update_interest(peer)

    # ------------------------------------------- swarm wire observability

    @staticmethod
    def _obs_key(peer: PeerConnection) -> str:
        """Stable telemetry key for one connection: a short peer-id
        prefix plus the transport address (the same facts status() and
        the ban list already expose — never the full 20-byte id).
        Memoized on the connection — the per-message accounting path
        must not rebuild the string per 16 KiB block."""
        key = peer.obs_key
        if key is None:
            host, port = peer.address or ("?", 0)
            key = peer.obs_key = f"{peer.peer_id[:4].hex()}@{host}:{port}"
        return key

    def _recv_charge(self, seconds: float, nbytes: int) -> None:
        """Account wire time/bytes to the ledger's ``recv`` stage.

        Batched: the accumulator flushes once per :data:`_RECV_FLUSH_OPS`
        events or :data:`_RECV_FLUSH_S` seconds of accumulated wait, so
        a 16 KiB-block hot loop pays one obs-lock acquisition per batch,
        not per message. The peer loop runs on the event loop thread, so
        the accumulator needs no lock of its own."""
        self._recv_s += seconds
        self._recv_bytes += nbytes
        self._recv_ops += 1
        if self._recv_ops >= _RECV_FLUSH_OPS or self._recv_s >= _RECV_FLUSH_S:
            self._recv_flush()

    def _recv_flush(self) -> None:
        if not self._recv_ops:
            return
        pipeline_ledger().record("recv", self._recv_bytes, self._recv_s)
        self._recv_s = 0.0
        self._recv_bytes = 0
        self._recv_ops = 0

    @staticmethod
    def _gate_key(peer_id, address) -> str:
        """Telemetry key for a connection refused BEFORE a
        PeerConnection existed (the accept-gate reject path)."""
        host, port = address or ("?", 0)
        return f"{peer_id[:4].hex()}@{host}:{port}"

    def _egress_charge(self, seconds: float, nbytes: int) -> None:
        """Account serve time/bytes to the ledger's ``egress`` stage
        (batched, the ``_recv_charge`` discipline — a seeder pushing
        thousands of blocks a second pays one obs-lock per batch)."""
        self._egress_s += seconds
        self._egress_bytes += nbytes
        self._egress_ops += 1
        if self._egress_ops >= _RECV_FLUSH_OPS or self._egress_s >= _RECV_FLUSH_S:
            self._egress_flush()

    def _egress_flush(self) -> None:
        if not self._egress_ops:
            return
        pipeline_ledger().record("egress", self._egress_bytes, self._egress_s)
        self._egress_s = 0.0
        self._egress_bytes = 0
        self._egress_ops = 0

    # ------------------------------------------------------- message loop

    async def _peer_loop(self, peer: PeerConnection) -> None:
        """All nine message handlers (torrent.ts:114-196, completed).

        The read is deliberately NOT wrapped in ``asyncio.wait_for``: at
        16 KiB blocks that is one timer handle allocated and cancelled
        per message (~6k/s/peer at full rate — measured as a top-5
        event-loop cost in the 8-leech profile). Dead-peer protection
        lives in ``_idle_sweep_loop`` instead: one timer per torrent,
        closing any transport whose ``last_rx`` went stale, which wakes
        this read with EOF exactly like the old per-message timeout.
        """
        try:
            while not self._stopping:
                # recv-stage accounting: time blocked on the socket WHILE
                # this peer owes us blocks is network-limited time (an
                # idle keepalive wait with nothing requested is not) —
                # the charge that lets attribution say "the network is
                # the bottleneck" instead of blaming disk
                waited_from = time.monotonic() if peer.inflight else None
                msg = await proto.read_message(peer.reader)
                if msg is None:
                    break
                peer.last_rx = time.monotonic()
                if waited_from is not None:
                    self._recv_charge(peer.last_rx - waited_from, 0)
                await self._handle_message(peer, msg)
        except (proto.ProtocolError, asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            self._drop_peer(peer)

    async def _handle_message(self, peer: PeerConnection, msg) -> None:
        # per-message-type byte/count accounting (bounded kind set); the
        # registry folds unknown kinds and >MAX_TRACKED_PEERS peers, so
        # this is O(1) dict work under one uncontended leaf lock
        okey = self._obs_key(peer)
        self._swarm_obs.on_message(
            okey, type(msg).__name__, _wire_payload_bytes(msg)
        )
        match msg:
            case proto.KeepAlive():
                pass
            case proto.Choke():
                peer.peer_choking = True
                self._swarm_obs.on_state(okey, peer_choking=True)
                if not peer.fast:
                    # BEP 3: choke silently voids outstanding requests.
                    # BEP 6: it doesn't — the peer explicitly rejects each
                    # one (the snub timer is the net under a peer that
                    # chokes and never sends the rejects).
                    self._release_inflight(peer)
            case proto.Unchoke():
                peer.peer_choking = False
                self._swarm_obs.on_state(okey, peer_choking=False)
                await self._fill_pipeline(peer)
            case proto.Interested():
                peer.peer_interested = True
                self._swarm_obs.on_state(okey, peer_interested=True)
                # Fast-path unchoke: when reciprocity slots are free, a
                # newly interested peer starts transferring NOW instead of
                # idling choked until the next 10 s rechoke tick (the tick
                # still re-ranks everyone by rate later). Without this,
                # every fresh connection wastes up to choke_interval
                # seconds — the dominant latency in small swarms.
                if not self.paused and peer.am_choking:
                    unchoked = sum(
                        1 for p in self.peers.values() if not p.am_choking
                    )
                    if unchoked < self.config.unchoke_slots + 1:
                        peer.am_choking = False
                        self._swarm_obs.on_state(okey, am_choking=False)
                        await proto.send_message(peer.writer, proto.Unchoke())
            case proto.NotInterested():
                peer.peer_interested = False
                self._swarm_obs.on_state(okey, peer_interested=False)
            case proto.Have(index):
                if 0 <= index < self.info.num_pieces:
                    if not peer.bitfield.has(index):
                        peer.bitfield.set(index)
                        self._avail[index] += 1
                        self._rarity_dirty = True
                    if self.super_seeding():
                        await self._ss_on_peer_have(peer, index)
                    # A Have can only turn interest ON, so this is O(1);
                    # the full vector interest recheck is reserved for
                    # bitfield replacement and our own piece completions
                    # (where interest can flip off).
                    if not self.bitfield.has(index) and self._piece_priority[index] > 0:
                        if not peer.am_interested:
                            peer.am_interested = True
                            await proto.send_message(peer.writer, proto.Interested())
                        # _fill_pipeline self-gates on choke state and
                        # allowed-fast grants — a choked fast peer that
                        # granted this very piece must still be asked.
                        # Refill only when this peer's pipeline is idle
                        # (or endgame): a busy pipeline refills itself on
                        # the next block via the hysteresis path, and in
                        # a cross-connected swarm per-Have refills are an
                        # O(pieces) scan times every completion broadcast
                        # (measured: ~40% of the seed-fanout CPU). A
                        # choked fast peer announcing a piece it GRANTED
                        # still refills immediately — its retained
                        # pre-choke inflight may never drain (rejects can
                        # be withheld), and this piece is its explicit
                        # offer.
                        if (
                            not peer.inflight
                            or self._endgame
                            or (peer.peer_choking and index in peer.allowed_fast_in)
                        ):
                            await self._fill_pipeline(peer)
            case proto.BitfieldMsg(raw):
                try:
                    new_bf = Bitfield(self.info.num_pieces, raw)
                except ValueError:
                    # construct-before-decrement: a bad bitfield must leave
                    # availability untouched (drop-peer will decrement the
                    # old one exactly once)
                    raise proto.ProtocolError("bad bitfield")
                await self._replace_bitfield(peer, new_bf)
            case proto.Request(index, begin, length):
                # malformed requests kill the connection HERE, in the
                # peer loop (queueing them would soften the protocol
                # error into a swallowed worker exception)
                if not validate_requested_block(self.info, index, begin, length):
                    raise proto.ProtocolError("invalid request")
                if self._serve_reactor.running:
                    # the reactor decouples the wire from the disk: the
                    # request queues per peer; a full queue is answered
                    # with an explicit reject (bounded hostile demand)
                    if not self._serve_reactor.submit(
                        peer.peer_id, (index, begin, length)
                    ):
                        self._serve_obs.on_reject(okey, "backpressure")
                        if peer.fast:
                            await proto.send_message(
                                peer.writer,
                                proto.RejectRequest(index, begin, length),
                            )
                else:
                    # no pool (stopped torrent, direct-drive tests):
                    # serve inline, the legacy path
                    await self._serve_request(peer, index, begin, length)
            case proto.Piece(index, begin, block):
                await self._ingest_block(peer, index, begin, block)
            case proto.Cancel(index, begin, length):
                # requests still queued in the reactor are cancellable
                # (in-flight ones are not — we serve them; BEP 3 allows
                # either). Fast peers get the explicit BEP 6 reject.
                gone = self._serve_reactor.cancel(
                    peer.peer_id, lambda it: it == (index, begin, length)
                )
                if gone:
                    self._serve_obs.on_queue_cancel(len(gone))
                    if peer.fast:
                        for (ci, cb, cl) in gone:
                            await proto.send_message(
                                peer.writer, proto.RejectRequest(ci, cb, cl)
                            )
            case proto.HaveAll() | proto.HaveNone():
                if not peer.fast:
                    raise proto.ProtocolError("have_all/have_none without fast ext")
                new_bf = Bitfield(self.info.num_pieces)
                if isinstance(msg, proto.HaveAll):
                    new_bf.from_numpy(np.ones(self.info.num_pieces, dtype=bool))
                await self._replace_bitfield(peer, new_bf)
            case proto.SuggestPiece(index):
                if peer.fast and 0 <= index < self.info.num_pieces:
                    # bounded hint list, most recent first
                    if index in peer.suggested:
                        peer.suggested.remove(index)
                    peer.suggested.insert(0, index)
                    del peer.suggested[16:]
            case proto.AllowedFast(index):
                if peer.fast and 0 <= index < self.info.num_pieces:
                    peer.allowed_fast_in.add(index)
                    if (
                        peer.peer_choking
                        and peer.bitfield.has(index)
                        and not self.bitfield.has(index)
                    ):
                        await self._fill_pipeline(peer)
            case proto.RejectRequest(index, begin, length):
                if not peer.fast:
                    raise proto.ProtocolError("reject_request without fast ext")
                blk = (index, begin, length)
                self._swarm_obs.on_reject(okey)
                if blk in peer.inflight:
                    peer.inflight.discard(blk)
                    peer.req_sent_at.pop(blk, None)
                    self._inflight_release(blk)
                    # Rejecting a request that was *issued under the grant*
                    # (i.e. while choked) withdraws it — otherwise the
                    # choked pipeline re-requests it forever. Rejects of
                    # ordinary unchoked-time requests (the normal BEP 6
                    # choke flow) must NOT burn the grant: it becomes
                    # useful exactly now that we are choked.
                    if blk in peer.inflight_choked:
                        peer.inflight_choked.discard(blk)
                        peer.allowed_fast_in.discard(index)
                    # A peer that rejects everything we ask for must not
                    # spin the request/reject loop at line rate: each
                    # refill resets the wall-clock snub timer, so count
                    # rejects instead and snub on a burst of them.
                    peer.rejects_since_block += 1
                    if peer.rejects_since_block >= 2 * self.config.pipeline_depth:
                        peer.snubbed_until = (
                            time.monotonic() + self.config.snub_timeout
                        )
                        self._swarm_obs.on_snub(okey)
                    else:
                        await self._fill_pipeline(peer)
            case proto.HashRequest():
                await self._serve_hash_request(peer, msg)
            case proto.Hashes() | proto.HashReject():
                # responses are routed by (sender, fields): another peer
                # echoing the same fields must not resolve — or poison —
                # a wait addressed to someone else
                key = (
                    peer.peer_id,
                    msg.pieces_root,
                    msg.base_layer,
                    msg.index,
                    msg.length,
                    msg.proof_layers,
                )
                fut = self._hash_fetches.get(key)
                if fut is not None and not fut.done():
                    fut.set_result(
                        msg.hash_list() if isinstance(msg, proto.Hashes) else None
                    )
            case proto.Extended(ext_id, payload):
                await self._handle_extended(peer, ext_id, payload)

    # ------------------------------------------------- BEP 52 hash serving

    def _hash_tree_cache(self):
        """Lazy per-torrent merkle layer cache for hybrid torrents.

        Hybrid `.torrent`s (BEP 52 upgrade path) carry a top-level
        ``piece layers`` dict alongside the v1 info; v2-capable peers on
        the v1 swarm may ask us for subtree hashes (messages 21-23).
        Returns None for plain v1 torrents — those requests get rejects.
        """
        if self._hash_cache is _UNSET:
            self._hash_cache = None
            layers_raw = self.metainfo.raw.get(b"piece layers")
            if isinstance(layers_raw, dict) and layers_raw:
                from torrent_tpu.models.hashes import HashTreeCache

                layers = {}
                for root, blob in layers_raw.items():
                    if isinstance(root, bytes) and len(root) == 32 and isinstance(blob, bytes):
                        layers[root] = tuple(
                            blob[i : i + 32] for i in range(0, len(blob), 32)
                        )
                if layers:
                    cache = HashTreeCache(layers, self.info.piece_length)
                    # single-piece files: their pieces root appears only
                    # in the info file tree, not in piece layers
                    cache.add_single_piece_roots(
                        r for r, _ in self._v2_file_roots() if r not in layers
                    )
                    self._hash_cache = cache
        return self._hash_cache

    def _v2_file_roots(self) -> list[tuple[bytes, int]]:
        """``(pieces_root, length)`` per file from the info file tree
        (hybrid torrents); empty for plain v1."""
        info_raw = self.metainfo.raw.get(b"info")
        if not isinstance(info_raw, dict):
            return []
        out = []

        def walk(node):
            if not isinstance(node, dict):
                return
            for k, v in node.items():
                if k == b"" and isinstance(v, dict):
                    pr = v.get(b"pieces root")
                    ln = v.get(b"length")
                    if isinstance(pr, bytes) and len(pr) == 32 and isinstance(ln, int):
                        out.append((pr, ln))
                else:
                    walk(v)

        walk(info_raw.get(b"file tree", {}))
        return out

    async def _fetch_hash_run(
        self, fields: tuple, req, deadline: float, per_peer: float
    ):
        """Ask connected peers (sequentially, short per-peer timeout) for
        one verified hash run; None when nobody delivers in time."""
        from torrent_tpu.models.hashes import verify_hash_response

        for peer in list(self.peers.values()):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            key = (peer.peer_id, *fields)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._hash_fetches[key] = fut
            try:
                await proto.send_message(peer.writer, proto.HashRequest(*fields))
                got = await asyncio.wait_for(fut, min(per_peer, remaining))
            except (asyncio.TimeoutError, ConnectionError, OSError):
                got = None
            finally:
                self._hash_fetches.pop(key, None)
            if got and verify_hash_response(req, got):
                return got
        return None

    async def fetch_v2_layers(self, timeout: float = 30.0, per_peer: float = 5.0) -> bool:
        """BEP 52 fetch side: pull missing piece layers from the swarm.

        A magnet-joined hybrid learns its info dict via ut_metadata, but
        piece layers live OUTSIDE the info dict — without them we can't
        serve hash requests onward. Every run is verified against the
        trusted ``pieces root`` before acceptance: small layers are
        fetched whole (the full layer reduces directly to the root),
        large ones in MAX_RUN chunks whose uncle proofs chain each chunk
        to the root independently. Peers are tried with a short per-peer
        timeout under one overall deadline (v1-only peers simply never
        answer message 21). Returns True when every multi-piece file's
        layer verified and installed (the torrent then serves onward).
        """
        async with self._fetch_layers_lock:
            return await self._fetch_v2_layers_locked(timeout, per_peer)

    async def _fetch_v2_layers_locked(self, timeout: float, per_peer: float) -> bool:
        from torrent_tpu.models.hashes import (
            HashRequestFields,
            HashTreeCache,
            MAX_RUN,
            _layer_height,
        )

        if self._hash_tree_cache() is not None:
            return True  # already have layers (authored/parsed from disk)
        roots = self._v2_file_roots()
        if not roots:
            return False  # not a hybrid torrent
        plen = self.info.piece_length
        base = _layer_height(plen)
        deadline = time.monotonic() + timeout
        layers: dict[bytes, tuple[bytes, ...]] = {}
        singles = []
        for root, length in roots:
            n_pieces = max(1, -(-length // plen))
            if n_pieces == 1:
                singles.append(root)
                continue
            padded = 1 << (n_pieces - 1).bit_length()
            run = min(padded, MAX_RUN)
            # chunks above MAX_RUN verify via uncle proofs up to the root
            proofs = (padded.bit_length() - 1) - (run.bit_length() - 1)
            got_all: list[bytes] = []
            for start in range(0, min(padded, n_pieces), run):
                fields = (root, base, start, run, proofs)
                req = HashRequestFields(*fields)
                got = await self._fetch_hash_run(fields, req, deadline, per_peer)
                if got is None:
                    return False
                got_all.extend(got[:run])
            layers[root] = tuple(got_all[:n_pieces])
        cache = HashTreeCache(layers, plen)
        cache.add_single_piece_roots(singles)
        self._hash_cache = cache
        return True

    async def _serve_hash_request(self, peer: PeerConnection, msg) -> None:
        from torrent_tpu.models.hashes import HashRequestFields

        fields = (msg.pieces_root, msg.base_layer, msg.index, msg.length, msg.proof_layers)
        cache = self._hash_tree_cache()
        served = None
        if cache is not None:
            # the first request per root rebuilds that file's merkle
            # levels (~200k sha256 for a 100k-piece layer) — off the
            # event loop, so piece traffic and timers keep flowing
            served = await asyncio.to_thread(
                cache.serve, HashRequestFields(*fields)
            )
        if served is None:
            await proto.send_message(peer.writer, proto.HashReject(*fields))
            return
        await proto.send_message(
            peer.writer, proto.Hashes(*fields, hashes=b"".join(served))
        )

    # ----------------------------------------------------- BEP 10 extensions

    def info_bytes(self) -> bytes:
        """Canonical serialized info dict (BEP 9 metadata payload)."""
        if self._info_bytes is None:
            from torrent_tpu.codec.bencode import bencode

            raw_info = self.metainfo.raw.get(b"info")
            if raw_info is not None:
                # sort_keys=False: the decoded dict preserves the file's
                # key order, so this re-encode is byte-exact and hashes
                # back to info_hash.
                self._info_bytes = bencode(raw_info, sort_keys=False)
            else:  # synthetic metainfo (tests): canonical order
                self._info_bytes = b""
        return self._info_bytes

    async def _handle_extended(self, peer: PeerConnection, ext_id: int, payload: bytes) -> None:
        """BEP 10 demux: ext handshake (0) or our ut_metadata id."""
        if not peer.ext.enabled:
            return  # never advertised the reserved bit; ignore
        if ext_id == 0:
            ext.decode_extended_handshake(payload, peer.ext)
            return
        if ext_id == ext.LOCAL_EXT_IDS[ext.UT_PEX]:
            if self.private:
                return  # BEP 27: ignore gossip a peer sends anyway
            pex = ext.decode_pex(payload)
            if pex is not None and pex.added:
                from torrent_tpu.net.types import AnnouncePeer

                self._connect_new_peers(
                    [AnnouncePeer(ip=h, port=p) for h, p in pex.added]
                )
            return
        if ext_id == ext.LOCAL_EXT_IDS[ext.UT_HOLEPUNCH]:
            await self._handle_holepunch(peer, payload)
            return
        if ext_id == ext.LOCAL_EXT_IDS[ext.LT_DONTHAVE]:
            # BEP 54: the peer retracts an announced piece — the inverse
            # of Have. Interest can flip OFF here, so the full vector
            # recheck runs (unlike the O(1) Have fast path).
            idx = ext.decode_donthave(payload)
            if idx is None or not (0 <= idx < self.info.num_pieces):
                return
            if peer.bitfield.has(idx):
                peer.bitfield.set(idx, False)
                self._avail[idx] -= 1
                self._rarity_dirty = True
                # The peer can no longer deliver blocks of this piece:
                # release them for other peers (the Choke/RejectRequest
                # treatment) — a BEP 54 peer without the fast extension
                # sends no rejects, so held blocks would stall until the
                # snub sweep otherwise.
                for blk in [b for b in peer.inflight if b[0] == idx]:
                    self._inflight_release(blk)
                    peer.inflight.discard(blk)
                    peer.inflight_choked.discard(blk)
                await self._update_interest(peer)
            return
        if ext_id == ext.LOCAL_EXT_IDS[ext.UT_METADATA]:
            msg = ext.decode_metadata_message(payload)
            if msg is None or peer.ext.ut_metadata_id == 0:
                return
            if msg.msg_type == ext.MsgType.REQUEST:
                info = self.info_bytes()
                piece = ext.metadata_piece(info, msg.piece) if info else None
                if piece is None:
                    reply = ext.encode_metadata_reject(msg.piece)
                else:
                    reply = ext.encode_metadata_data(msg.piece, len(info), piece)
                await proto.send_message(
                    peer.writer, proto.Extended(peer.ext.ut_metadata_id, reply)
                )
            # DATA/REJECT towards a complete torrent: nothing to do (the
            # magnet fetch path, session/metadata.py, has its own loop).

    # -------------------------------------------------- BEP 16 super-seed

    def super_seeding(self) -> bool:
        """True while BEP 16 mode is active (needs a complete torrent)."""
        return self._ss_active and self.bitfield.complete

    async def set_super_seeding(self, on: bool) -> None:
        """Toggle BEP 16 at runtime. Turning it ON only affects peers
        that connect afterwards — existing peers already saw the real
        bitfield, so they are exempted from the serve gate (hiding
        pieces they know about would only stall them); turning it OFF
        reveals everything to current peers."""
        was = self.super_seeding()
        if on and not was:
            for p in self.peers.values():
                p.ss_exempt = True
        self._ss_active = bool(on)
        if was and not self.super_seeding():
            await self._ss_reveal_all()

    def _ss_arrays(self) -> None:
        if self._ss_spread is None:
            n = self.info.num_pieces
            self._ss_spread = np.zeros(n, dtype=bool)
            self._ss_assigned = np.zeros(n, dtype=np.int32)

    def _ss_pick(self, peer: PeerConnection) -> list[int]:
        """Grant up to the outstanding quota of pieces to ``peer``:
        least-granted unspread pieces the peer doesn't already have."""
        self._ss_arrays()
        grants = []
        while len(peer.ss_unconfirmed) < self.config.super_seed_outstanding:
            mask = ~self._ss_spread & ~peer.bitfield.as_numpy()
            for q in peer.ss_advertised:
                mask[q] = False
            idxs = np.nonzero(mask)[0]
            if len(idxs) == 0:
                break
            q = int(idxs[np.argmin(self._ss_assigned[idxs])])
            self._ss_assigned[q] += 1
            peer.ss_advertised.add(q)
            peer.ss_unconfirmed.add(q)
            grants.append(q)
        return grants

    async def _ss_grant(self, peer: PeerConnection) -> None:
        for q in self._ss_pick(peer):
            await proto.send_message(peer.writer, proto.Have(index=q))

    async def _ss_on_peer_have(self, peer: PeerConnection, index: int) -> None:
        """BEP 16 confirmation: a piece we granted is 'spread' once a
        peer we did NOT grant it to announces it — the only way it can
        have the piece is that a grantee uploaded it onward. A grantee's
        own Have proves nothing (it downloaded from us), EXCEPT when
        every connected peer now has the piece — then there is nobody
        left to upload to and holding the grant open would wedge the
        grantee's quota (this also covers the one-peer swarm, where
        strict BEP 16 would deadlock with nobody to confirm)."""
        self._ss_arrays()
        if self._ss_spread[index]:
            return
        if index in peer.ss_advertised:
            everyone_has = all(
                p.bitfield.has(index) for p in self.peers.values()
            )
            if not everyone_has:
                return  # grantee finished ITS download: not evidence
        self._ss_spread[index] = True
        # confirmation releases EVERY grantee's outstanding entry for
        # this piece (a double-granted piece must not leak quota slots)
        for p in list(self.peers.values()):
            if index in p.ss_unconfirmed:
                p.ss_unconfirmed.discard(index)
                try:
                    await self._ss_grant(p)
                except (ConnectionError, OSError):
                    continue  # peer went away; grants return via _drop_peer
        if bool(self._ss_spread.all()):
            # one full copy is out in the swarm: mission accomplished —
            # revert to plain seeding (rarest-first swarm dynamics take
            # over from here, per BEP 16's own guidance)
            self._ss_active = False
            await self._ss_reveal_all()

    async def _ss_reveal_all(self) -> None:
        """Exit super-seed mode: advertise every still-hidden piece to
        every connected peer (bitfields can't be resent mid-connection;
        Haves are always legal)."""
        for p in list(self.peers.values()):
            hidden = [
                i
                for i in range(self.info.num_pieces)
                if self.bitfield.has(i) and i not in p.ss_advertised
            ]
            p.ss_advertised.update(hidden)
            try:
                # one batched write + one drain per peer: a per-message
                # drain here would stall this peer loop for
                # num_pieces x num_peers round-trips on big torrents
                p.writer.write(
                    b"".join(
                        proto.encode_message(proto.Have(index=i)) for i in hidden
                    )
                )
                await p.writer.drain()
            except (ConnectionError, OSError):
                continue

    # ---------------------------------------------------- BEP 55 holepunch

    async def _handle_holepunch(self, peer: PeerConnection, payload: bytes) -> None:
        """Relay/act on a ut_holepunch frame (BEP 55 NAT traversal).

        As relay: a RENDEZVOUS naming a peer we're connected to gets
        simultaneous CONNECTs to both endpoints; unknown targets get an
        ERROR. As endpoint: a CONNECT is an invitation to dial NOW (the
        other side is dialing us at this instant — the parallel SYNs are
        what punch the NAT mappings open; on loopback tests it is simply
        an introduction service).
        """
        msg = ext.decode_holepunch(payload)
        if msg is None:
            return
        if self.private:
            # BEP 27: a private torrent's peers come from its trackers
            # ONLY — a relayed introduction is an off-tracker peer source
            # exactly like PEX, which is likewise disabled
            return
        if msg.msg_type == ext.HolepunchType.RENDEZVOUS:
            target = None
            for p in self.peers.values():
                addr = p.dial_address()
                if addr is not None and addr == msg.addr and p is not peer:
                    target = p
                    break
            initiator_addr = peer.dial_address()
            if target is None or initiator_addr is None:
                reply = ext.HolepunchMessage(
                    ext.HolepunchType.ERROR, msg.addr,
                    err_code=ext.HolepunchError.NOT_CONNECTED,
                )
                await self._send_holepunch(peer, reply)
                return
            if not target.ext.ut_holepunch_id:
                reply = ext.HolepunchMessage(
                    ext.HolepunchType.ERROR, msg.addr,
                    err_code=ext.HolepunchError.NO_SUPPORT,
                )
                await self._send_holepunch(peer, reply)
                return
            await self._send_holepunch(
                target, ext.HolepunchMessage(ext.HolepunchType.CONNECT, initiator_addr)
            )
            await self._send_holepunch(
                peer, ext.HolepunchMessage(ext.HolepunchType.CONNECT, msg.addr)
            )
            return
        if msg.msg_type == ext.HolepunchType.CONNECT:
            # an explicit introduction: dial NOW, bypassing the
            # seeds-don't-dial policy in _connect_new_peers — the other
            # endpoint is dialing us at this instant and the simultaneous
            # SYNs are the whole point of BEP 55
            addr = msg.addr
            known = {p.address for p in self.peers.values() if p.address} | {
                p.dial_address() for p in self.peers.values()
            }
            if addr in known or addr in self._dialing:
                return
            if len(self.peers) + len(self._dialing) >= self.config.max_peers:
                return  # same budget every dial path honors — a relay
                # streaming CONNECT frames must not mint unbounded dials
            if addr[0] in self._banned or (
                self.ip_filter is not None and self.ip_filter.blocked(addr[0])
            ):
                return
            self._dialing.add(addr)
            self._spawn(self._dial(addr, None))
            return
        if msg.msg_type == ext.HolepunchType.ERROR:
            log.debug(
                "holepunch rendezvous for %s failed: code %d", msg.addr, msg.err_code
            )

    async def _send_holepunch(self, peer: PeerConnection, msg) -> bool:
        if not peer.ext.ut_holepunch_id:
            return False
        try:
            payload = ext.encode_holepunch(msg)
        except (OSError, OverflowError, ValueError):
            # hostname instead of a numeric address, or a port outside
            # u16 — unencodable targets are a caller error, not a reason
            # to kill the peer loop
            return False
        await proto.send_message(
            peer.writer, proto.Extended(peer.ext.ut_holepunch_id, payload)
        )
        return True

    async def holepunch_rendezvous(
        self, relay_peer_id: bytes, target: tuple[str, int]
    ) -> bool:
        """Ask a connected relay peer to introduce us to ``target``
        (BEP 55 initiator side). True if the request was sent."""
        relay = self.peers.get(relay_peer_id)
        if relay is None or not relay.ext.ut_holepunch_id:
            return False
        return await self._send_holepunch(
            relay, ext.HolepunchMessage(ext.HolepunchType.RENDEZVOUS, target)
        )

    # ------------------------------------------------------------- leeching

    async def _update_interest(self, peer: PeerConnection) -> None:
        # vectorized: "peer has any wanted piece we're missing" without a
        # Python scan per have/bitfield message
        want = bool(
            np.any(
                peer.bitfield.as_numpy()
                & ~self.bitfield.as_numpy()
                & (self._piece_priority > 0)
            )
        )
        if want and not peer.am_interested:
            peer.am_interested = True
            self._swarm_obs.on_state(self._obs_key(peer), am_interested=True)
            await proto.send_message(peer.writer, proto.Interested())
        elif not want and peer.am_interested:
            peer.am_interested = False
            self._swarm_obs.on_state(self._obs_key(peer), am_interested=False)
            await proto.send_message(peer.writer, proto.NotInterested())
        if want:
            # self-gated: no-ops while choked unless allowed-fast applies
            await self._fill_pipeline(peer)

    def _rebuild_rarity(self) -> None:
        """Wanted missing pieces, highest file priority first, then
        rarest-first with a stable random tiebreak — or in index order
        when ``config.sequential`` (streaming playback wants the front
        of the file, not the globally rarest piece)."""
        missing = np.flatnonzero(
            (~self.bitfield.as_numpy()) & (self._piece_priority > 0)
        )
        if self.config.sequential:
            order = np.lexsort((missing, -self._piece_priority[missing]))
        else:
            jitter = np.random.random(len(missing))
            order = np.lexsort(
                (jitter, self._avail[missing], -self._piece_priority[missing])
            )
        self._rarity_order = missing[order].tolist()
        self._rarity_dirty = False

    def _blocks_of(self, index: int):
        plen = piece_length(self.info, index)
        for begin in range(0, plen, BLOCK_SIZE):
            yield (index, begin, min(BLOCK_SIZE, plen - begin))

    def _missing_blocks(self, index: int):
        partial = self._partials.get(index)
        for blk in self._blocks_of(index):
            if partial is not None and blk[1] in partial.received:
                continue
            yield blk

    async def _fill_pipeline(self, peer: PeerConnection) -> None:
        """Rarest-first picking + pipelining; endgame duplication.

        While choked, a BEP 6 peer can still be asked for its allowed-fast
        grants — candidate pieces are then restricted to that set.
        """
        if self.paused or self.bitfield.complete or not self._wanted_remaining():
            return
        choked_fast = peer.peer_choking and peer.fast and bool(peer.allowed_fast_in)
        if peer.peer_choking and not choked_fast:
            return
        if peer.snubbed and not self._endgame:
            return  # earns requests back by delivering a block
        budget = self.config.pipeline_depth - len(peer.inflight)
        if budget <= 0:
            return
        if (
            not self._endgame
            and peer.fill_starved
            and peer.inflight
            and time.monotonic() - peer.last_fill_at < 0.05
        ):
            # The last full scan could NOT fill this peer's budget (the
            # swarm is contended around it) and it ran <50 ms ago with
            # the pipeline still non-empty: skip the O(pieces) rescan.
            # In an 8-leech mesh the per-block hysteresis otherwise
            # re-runs a ~150 us scan at line rate for ~1-block yields —
            # measured as the top CPU cost of a fanout. Uncontended
            # peers (full-budget picks) and empty pipelines never wait.
            return
        peer.last_fill_at = time.monotonic()
        # direct bool-array views for the scan loops: Bitfield.has() is a
        # bounds-checked method call, and a deep rarity scan makes tens of
        # millions of them per fanout transfer (measured ~20% of seed-side
        # CPU). The picking phase below is await-free, so the snapshots
        # cannot go stale mid-scan.
        have_arr = self.bitfield.as_numpy()
        peer_arr = peer.bitfield.as_numpy()
        wanted: list[tuple[int, int, int]] = []

        def pickable(index: int) -> bool:
            return not peer.peer_choking or index in peer.allowed_fast_in

        def take_from(index: int) -> bool:
            # Saturated-piece fast path, exact for partial-less pieces:
            # the mirror counts distinct requested blocks, and a fresh
            # piece has no received-but-still-counted blocks, so mirror
            # == n_blocks means literally every block is requested. Under
            # fanout MOST deep-scanned pieces are in this state. Pieces
            # with a partial keep the full block iteration — their
            # received set can overlap stale outstanding requests, and a
            # count-based skip there can starve the one unrequested block
            # until a snub timeout.
            if index not in self._partials:
                n_blocks = (
                    piece_length(self.info, index) + BLOCK_SIZE - 1
                ) // BLOCK_SIZE
                if self._piece_inflight[index] >= n_blocks:
                    return False
            for blk in self._missing_blocks(index):
                if self._inflight_count[blk] > 0 or blk in peer.inflight:
                    continue
                wanted.append(blk)
                if len(wanted) >= budget:
                    return True
            return False

        # Prefer finishing partial pieces, then rarest-first fresh pieces.
        # Webseed-reserved partials are skipped: the HTTP fetch owns them
        # (racing it would double-download; endgame below still covers
        # them so a dead webseed can't stall completion).
        for index, partial in list(self._partials.items()):
            if partial.webseed:
                continue
            if (
                peer_arr[index]
                and not have_arr[index]
                and self._piece_priority[index] > 0  # deselected partials
                # (e.g. resumed then deselected) must not outrank wanted
                and pickable(index)
            ):
                if take_from(index):
                    break
        # Active stream windows outrank everything below: a parked HTTP
        # reader is latency-bound on exactly these pieces. Consulted
        # directly (not via the priority array) so window advances are
        # O(window) with no rarity rebuild.
        if len(wanted) < budget and self._stream_positions:
            for first, n in sorted(self._stream_positions.values()):
                for index in range(first, min(first + n, self.info.num_pieces)):
                    if (
                        have_arr[index]
                        or index in self._partials
                        or self._piece_priority[index] <= 0
                        or not peer_arr[index]
                        or not pickable(index)
                    ):
                        continue
                    if take_from(index):
                        break
                if len(wanted) >= budget:
                    break
        # BEP 6 suggest-piece hints outrank plain rarest-first: the sender
        # says these are cheap for it to serve (e.g. still in cache)
        if len(wanted) < budget:
            for index in peer.suggested:
                if (
                    have_arr[index]
                    or index in self._partials
                    or not peer_arr[index]
                    or not pickable(index)
                ):
                    continue
                if take_from(index):
                    break
        if len(wanted) < budget:
            if self._rarity_dirty:
                self._rebuild_rarity()
            done_prefix = 0
            for index in self._rarity_order:
                if have_arr[index]:
                    done_prefix += 1
                    continue
                if (
                    index in self._partials
                    or not peer_arr[index]
                    or not pickable(index)
                ):
                    continue
                if take_from(index):
                    break
            # The order never drops completed pieces on its own, so late
            # in a download every fill wades through a mostly-done list.
            # When the scanned prefix is dominated by finished pieces,
            # schedule a rebuild (vectorized, drops them all at once).
            if done_prefix > 64 and done_prefix * 2 > len(self._rarity_order):
                self._rarity_dirty = True

        if not wanted:
            if peer.peer_choking:
                # The choked-fast path must never trip global endgame:
                # "every granted piece is busy elsewhere" says nothing
                # about the swarm as a whole.
                peer.fill_starved = True
                return
            if self._wanted_remaining() > self._tail_threshold():
                # Everything THIS peer can see is requested somewhere,
                # but the download is nowhere near its tail — that is
                # CONTENTION, not endgame. Entering endgame here floods
                # the swarm: every received block then broadcasts
                # cancels and re-runs eager refills (measured in an
                # 8-leech mesh: mid-download endgame entry put a cancel
                # broadcast plus an O(pieces) scan behind every block).
                # Mark starved; the 50 ms gate paces the rescans.
                # (Checked BEFORE building `remaining` — the contended
                # path must not pay the O(missing x blocks) comprehension
                # it is about to discard.)
                peer.fill_starved = True
                return
            # Endgame: everything missing is in flight somewhere — duplicate
            # requests so one slow peer can't stall completion.
            remaining = [
                blk
                for i in self.bitfield.missing()
                if peer_arr[i]
                and pickable(i)
                and self._piece_priority[i] > 0
                for blk in self._missing_blocks(i)
                if blk not in peer.inflight
            ]
            if not remaining:
                peer.fill_starved = True
                return
            self._endgame = True
            random.shuffle(remaining)
            wanted = remaining[:budget]

        peer.fill_starved = len(wanted) < budget
        if not peer.inflight:
            # fresh pipeline: restart the snub clock so an idle-but-honest
            # peer isn't condemned for the time it spent choked
            peer.last_block_rx = time.monotonic()
        # one coalesced write + drain for the whole batch: a drain per
        # Request yields to the event loop per 16 KiB asked for
        proto.raise_if_closing(peer.writer)
        sent_at = time.monotonic()
        for blk in wanted:
            peer.inflight.add(blk)
            peer.req_sent_at[blk] = sent_at  # block-RTT anchor (obs/swarm)
            if peer.peer_choking:
                peer.inflight_choked.add(blk)  # issued under an allowed-fast grant
            self._inflight_add(blk)
            peer.writer.write(proto.encode_message(proto.Request(*blk)))
        await peer.writer.drain()
        self._swarm_obs.on_depth(self._obs_key(peer), len(peer.inflight))

    async def _ingest_block(self, peer: PeerConnection, index, begin, block) -> None:
        """(torrent.ts:183-193) + assembly, verification, have broadcast."""
        if not validate_received_block(self.info, index, begin, len(block)):
            raise proto.ProtocolError("invalid piece block geometry")
        if self.paused:
            # blocks served before the peer processed our pause-time
            # cancels are dropped (progress must freeze; they'll be
            # re-requested after resume)
            return
        blk = (index, begin, len(block))
        req_at = peer.req_sent_at.pop(blk, None)
        if blk in peer.inflight:
            peer.inflight.discard(blk)
            peer.inflight_choked.discard(blk)
            self._inflight_release(blk)
        peer.bytes_down += len(block)
        peer.last_block_rx = time.monotonic()
        peer.snubbed_until = 0.0  # delivering redeems
        peer.rejects_since_block = 0
        okey = self._obs_key(peer)
        # block round-trip + byte accounting (obs/swarm); the RTT also
        # feeds the shared log2 family SLO p99_ms=…:block_rtt reads
        self._swarm_obs.on_block(
            okey, len(block),
            (peer.last_block_rx - req_at) if req_at is not None else None,
        )
        self._swarm_obs.on_depth(okey, len(peer.inflight))
        pacing_s = 0.0
        if self.download_bucket is not None or not self.own_download_bucket.unlimited:
            # pacing inside the peer loop applies TCP backpressure: the
            # reader stops draining this peer until tokens free up. The
            # ``pacing`` flag exempts the peer from the snub sweep for
            # the whole wait — under a low cap with many peers the FIFO
            # queue latency alone can exceed snub_timeout, and cancelling
            # a delivering peer's requests there would churn duplicates.
            peer.pacing = True
            t_pace = time.monotonic()
            try:
                if self.download_bucket is not None:
                    await self.download_bucket.take(len(block))
                await self.own_download_bucket.take(len(block))
            finally:
                peer.pacing = False
                peer.last_block_rx = time.monotonic()
                pacing_s = peer.last_block_rx - t_pace
        # the recv stage owns this block's bytes — plus the download-cap
        # pacing wait, which models a slow link exactly like the socket
        # wait does (the ledger's wire tier ahead of `read`)
        self._recv_charge(pacing_s, len(block))
        if self.bitfield.has(index):
            return  # duplicate from endgame
        partial = self._partials.get(index)
        if partial is None:
            partial = self._partials[index] = _PartialPiece(
                index=index,
                length=piece_length(self.info, index),
                buffer=bytearray(piece_length(self.info, index)),
            )
        if begin in partial.received:
            return
        partial.buffer[begin : begin + len(block)] = block
        partial.received.add(begin)
        partial.contributors.add(
            (peer.peer_id, peer.address[0] if peer.address else None)
        )
        self.downloaded += len(block)

        blk_key = (index, begin, len(block))
        if self._endgame or self._inflight_count[blk_key] > 0:
            # other copies of this block are still in flight (endgame
            # duplication — possibly from an endgame that has since been
            # exited): cancel them on arrival. Keyed on the live
            # duplicate count, not the flag, so no copy is ever
            # downloaded redundantly to completion; outside endgame the
            # count is 0 and this costs one dict lookup.
            await self._cancel_everywhere(blk_key, except_peer=peer)

        if partial.complete:
            await self._finish_piece(partial)
            if self.peers.get(peer.peer_id) is not peer:
                return  # this very peer got banned/dropped by the verify
        # Refill with hysteresis: topping up the one freed slot per block
        # re-runs the picker per block (an O(pieces) scan each — measured
        # at ~40% of a fast transfer's CPU, O(n²) over a download). Let
        # the pipeline drain to half depth, then refill to full. Endgame
        # refills eagerly: duplication wants every slot it can get.
        if (
            self._endgame
            or len(peer.inflight) <= self.config.pipeline_depth // 2
        ):
            await self._fill_pipeline(peer)

    async def _cancel_everywhere(self, blk, except_peer) -> None:
        # snapshot: the sends await, and a peer registering/leaving
        # mid-iteration would mutate the dict under us
        for p in list(self.peers.values()):
            if p is except_peer or blk not in p.inflight:
                continue
            p.inflight.discard(blk)
            p.inflight_choked.discard(blk)
            p.req_sent_at.pop(blk, None)
            self._inflight_release(blk)
            self._swarm_obs.on_endgame_cancel(self._obs_key(p))
            try:
                await proto.send_message(p.writer, proto.Cancel(*blk))
            except (ConnectionError, OSError):
                pass

    async def _finish_piece(self, partial: _PartialPiece) -> str:
        """Verify → persist → have-broadcast (the §8.3 missing hook).

        Returns an outcome: ``"ok"``, ``"corrupt"`` (hash mismatch),
        ``"io_error"`` (persist failed), or ``"stale"`` (another path
        already finished this piece). Callers that attribute blame — the
        webseed loop's per-URL strike counter — must distinguish corrupt
        data from a local disk problem.

        With the TPU hasher, completed pieces from concurrent peers are
        verified as one device batch (the swarm-ingest face of the hash
        plane); otherwise per-piece hashlib off-thread.
        """
        if self._partials.get(partial.index) is not partial:
            # Another path (endgame peer vs webseed) already finished or
            # reset this piece — finishing it twice would double-count
            # stats and KeyError on the second removal.
            return "stale"
        del self._partials[partial.index]
        data = bytes(partial.buffer)
        expected = self.info.pieces[partial.index]
        if not await self._verify_piece_data(partial.index, data, expected):
            log.warning("piece %d failed verification; re-requesting", partial.index)
            self.downloaded -= partial.length  # don't count poisoned data
            self._credit_corruption(partial.contributors)
            return "corrupt"
        self._absolve(partial.contributors)
        base = partial.index * self.info.piece_length
        try:
            if len(data) <= INLINE_IO_MAX:
                self._write_piece(base, data)  # µs-scale pwrite: no hop
            else:
                await asyncio.to_thread(self._write_piece, base, data)
        except StorageError as e:
            log.error("failed to persist piece %d: %s", partial.index, e)
            return "io_error"
        self.bitfield.set(partial.index)
        self._notify_piece(partial.index)
        if self._piece_priority[partial.index] > 0:
            self._wanted_missing = max(0, self._wanted_missing - 1)
        if self.bitfield.count() % 16 == 0:
            self._checkpoint()  # periodic progress checkpoint
        # snapshot: each send awaits, and an inbound peer registering
        # during the broadcast mutates self.peers (observed as
        # "dictionary keys changed during iteration" killing the
        # ingesting peer's loop in an 8-leech fanout swarm)
        for p in list(self.peers.values()):
            if self.peers.get(p.peer_id) is not p:
                continue  # dropped during an earlier send's await
            try:
                await proto.send_message(p.writer, proto.Have(index=partial.index))
                if p.am_interested:
                    await self._update_interest(p)
            except (ConnectionError, OSError):
                # a dead writer here must not tear down the INGESTING
                # peer's loop, and interest updates on a dropped peer
                # would assign inflight blocks nothing will ever release
                pass
        await self._maybe_completed()
        return "ok"

    async def _maybe_completed(self) -> None:
        """Transition to seeding once every *wanted* piece is on disk.

        With the default everything-wanted mask this is the classic
        bitfield-complete transition; under file selection the torrent
        seeds what it has once the selection is satisfied (``left`` is 0,
        so the tracker gets its BEP 3 ``completed``).
        """
        if self.state != TorrentState.DOWNLOADING:
            return
        self._recount_wanted()  # authoritative at the decision point
        if self._wanted_missing:
            return
        self.state = TorrentState.SEEDING
        self._endgame = False
        # the download's tail recv charges must be attributable NOW — a
        # doctor/bench reading /v1/pipeline right after completion must
        # not miss the last partial batch
        self._recv_flush()
        if not self._completed_reported:
            # BEP 3: `completed` at most once per download — a piece
            # lost (BEP 54) and re-fetched, or a selection widened and
            # re-satisfied, must not inflate tracker snatch counts
            self._pending_completed = True
            self._completed_reported = True
        self._checkpoint()
        self.on_complete.set()
        self.request_peers()  # announce `completed` promptly

    def _write_piece(self, base: int, data: bytes) -> None:
        for off in range(0, len(data), BLOCK_SIZE):
            self.storage.set(base + off, data[off : off + BLOCK_SIZE])

    def _credit_corruption(self, contributors) -> None:
        """Failure detection: strike every contributor address of a corrupt
        piece (the faulty block can't be attributed more precisely without
        per-block hashes); ban at the threshold. Strikes persist across
        reconnects and decay via ``_absolve`` on verified pieces.
        """
        for peer_id, _ in contributors:
            peer = self.peers.get(peer_id)
            if peer is not None:
                peer.corrupt_pieces += 1
                self._swarm_obs.on_corrupt(self._obs_key(peer))
        # one corrupt piece = one strike per ADDRESS — two NATed peers
        # sharing an IP must not double-strike it for the same failure
        for ip in {ip for _, ip in contributors}:
            if ip is None or ip in self._banned:
                continue
            if (
                ip not in self._corruption
                and len(self._corruption) >= MAX_CORRUPTION_IPS
            ):
                # strike table at capacity: forget the least-incriminated
                # address rather than grow per attacker-minted IP
                drop = min(self._corruption, key=self._corruption.__getitem__)
                del self._corruption[drop]
            self._corruption[ip] += 1
            if self._corruption[ip] >= self.config.max_corrupt_pieces:
                if len(self._banned) >= MAX_BANNED_IPS:
                    # ban list full: the oldest ban ages out (FIFO) — an
                    # attacker cycling addresses churns the list instead
                    # of growing it for the life of the session
                    del self._banned[next(iter(self._banned))]
                self._banned[ip] = None
                log.warning(
                    "banning %s: %d corrupt pieces", ip, self._corruption[ip]
                )
                for p in list(self.peers.values()):
                    if p.address and p.address[0] == ip:
                        self._drop_peer(p)

    def _absolve(self, contributors) -> None:
        """A verified piece sheds one strike per contributor address."""
        for ip in {ip for _, ip in contributors}:
            if ip is not None and self._corruption[ip] > 0:
                self._corruption[ip] -= 1

    # ------------------------------------------------- ingest verification

    async def _verify_piece_data(self, index: int, data: bytes, expected: bytes) -> bool:
        """One piece's hash check, batched onto the TPU when available.

        Concurrent finishers pile into ``_verify_pending`` and a single
        micro-batch flush hashes them all in one device launch; callers
        await their own piece's future. CPU mode: hashlib off-thread.
        v2 torrents (session/v2.py): the expected digest is the piece's
        merkle subtree root — SHA-256 leaves folded per BEP 52, off the
        event loop (≤64 leaves per piece; the batched device planes pay
        off on the full-recheck path, not per-piece ingest).
        """
        if self.v2:
            from torrent_tpu.models.merkle import piece_root_cpu

            pad = self.info.piece_pad_leaves[index]
            if (
                self.config.hasher == "tpu"
                and len(data) == self.info.piece_length
                and pad == self.info.piece_length // 16384
            ):
                # Full-subtree piece: batch onto the device leaf plane
                # with every other concurrent finisher — the same
                # micro-batch machinery as v1 (_flush_verify_batch routes
                # on self.v2); tail pieces (short data / oversized pad)
                # fold on the CPU below.
                #
                # Crossover, RECORDED in .bench/v2_crossover.json
                # (2026-08-01, this host): piece_root_cpu sustains
                # 1.24-1.36 GiB/s (0.72 ms per 1 MiB piece incl. tree
                # reduction) vs the banked 11.9 GiB/s plane + ~55 ms
                # relay dispatch — the batch wins at ≥87
                # concurrently-finishing 1 MiB pieces here (312 at
                # 256 KiB), but on a co-located TPU host (~1 ms
                # dispatch) at ≤2 (≤6 at 256 KiB). Either
                # way the verify leaves the event loop, which is what
                # ingest latency cares about; a device failure falls back
                # to hashlib inside the flush.
                fut: asyncio.Future = asyncio.get_running_loop().create_future()
                self._verify_pending.append((index, data, expected, fut))
                if not self._verify_flushing:
                    self._verify_flushing = True
                    self._spawn(self._flush_verify_batch(), name="verify-batch")
                return await fut
            if len(data) <= INLINE_IO_MAX:
                return piece_root_cpu(data, pad) == expected
            root = await asyncio.to_thread(piece_root_cpu, data, pad)
            return root == expected
        if self.verifier is None or self.config.hasher != "tpu":
            if len(data) <= INLINE_IO_MAX:
                return hashlib.sha1(data).digest() == expected
            digest = await asyncio.to_thread(lambda: hashlib.sha1(data).digest())
            return digest == expected
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._verify_pending.append((index, data, expected, fut))
        if not self._verify_flushing:
            self._verify_flushing = True
            self._spawn(self._flush_verify_batch(), name="verify-batch")
        return await fut

    async def _flush_verify_batch(self) -> None:
        """Drain the pending-verification queue in device batches."""
        try:
            # one event-loop tick lets concurrent _finish_piece calls join
            await asyncio.sleep(0)
            while self._verify_pending:
                batch = self._verify_pending[: self.config.verify_batch_size]
                del self._verify_pending[: len(batch)]
                pieces = [b[1] for b in batch]
                expected = [b[2] for b in batch]
                device_fn = (
                    self._verify_batch_device_v2 if self.v2 else self._verify_batch_device
                )
                try:
                    ok = await asyncio.to_thread(device_fn, pieces, expected)
                except Exception as e:  # device trouble: fail safe to hashlib
                    log.warning("tpu ingest verify failed (%s); hashlib fallback", e)
                    if self.v2:
                        from torrent_tpu.models.merkle import piece_root_cpu

                        lpp = self.info.piece_length // 16384
                        ok = await asyncio.to_thread(
                            lambda: [
                                piece_root_cpu(p, lpp) == e2
                                for p, e2 in zip(pieces, expected)
                            ]
                        )
                    else:
                        ok = await asyncio.to_thread(
                            lambda: [
                                hashlib.sha1(p).digest() == e2
                                for p, e2 in zip(pieces, expected)
                            ]
                        )
                for (_, _, _, fut), good in zip(batch, ok):
                    if not fut.done():
                        fut.set_result(bool(good))
        finally:
            self._verify_flushing = False
            for idx, _, _, fut in self._verify_pending:
                if not fut.done():
                    fut.set_result(False)  # torn down mid-flight: re-request
            self._verify_pending.clear()

    def _verify_batch_device(self, pieces: list[bytes], expected: list[bytes]):
        from torrent_tpu.ops.padding import digests_to_words

        digests = self.verifier.hash_pieces(pieces)
        want = digests_to_words(expected)
        got = digests_to_words(digests)
        return (got == want).all(axis=1)

    def _verify_batch_device_v2(self, pieces: list[bytes], expected: list[bytes]):
        """Batched BEP 52 ingest verify: ONE leaf-plane dispatch plus the
        fused merkle pair reduction for every concurrently-finishing
        full-subtree piece (only those are queued — _verify_piece_data
        folds tails on the CPU, where the pad geometry is per-piece)."""
        from torrent_tpu.models.merkle import (
            piece_roots_from_leaves,
            words32_to_digests,
        )
        from torrent_tpu.models.v2 import _leaf_words_from_chunks

        lpp = self.info.piece_length // 16384
        # each full piece IS a block-aligned chunk: feed them straight to
        # the leaf plane instead of joining into a second copy of the
        # whole batch (256 x 1 MiB pieces would duplicate ~256 MiB)
        leaves = _leaf_words_from_chunks(
            iter(pieces), sum(len(p) for p in pieces), "auto"
        )
        roots = words32_to_digests(piece_roots_from_leaves(leaves, lpp))
        return [r == e for r, e in zip(roots, expected)]

    # ------------------------------------------------------------- seeding

    async def _piece_lost(self, index: int) -> None:
        """BEP 54 self-healing: an announced piece turned unreadable.

        BEP 3 cannot retract a Have, so without this a seed with a bad
        sector serves refusals forever while peers keep asking. Instead:
        drop the piece from our bitfield (the picker re-wants it and the
        swarm re-supplies it), fall back from SEEDING if needed, tell
        lt_donthave-capable peers the truth, and re-evaluate interest —
        we may need to fetch again from peers we'd gone not-interested on.
        """
        if not self.bitfield.has(index):
            return
        log.warning("piece %d lost (read failure under an announced piece)", index)
        self.bitfield.set(index, False)
        self._serve_cache.pop(index, None)
        # without this the re-downloaded piece verifies in memory but
        # every block write is suppressed as a duplicate and the disk
        # keeps the bad bytes
        self.storage.unmark_piece_written(index)
        self._rarity_dirty = True
        self._recount_wanted()
        if self.state == TorrentState.SEEDING and self._wanted_missing:
            self.state = TorrentState.DOWNLOADING
            self.on_complete.clear()
            self._spawn_seed_loops()
            self.request_peers()
        self._checkpoint()
        payload = ext.encode_donthave(index)
        for p in list(self.peers.values()):
            if self.peers.get(p.peer_id) is not p:
                continue  # dropped during an earlier send's await: an
                # interest update on it would assign inflight blocks
                # nothing will ever release (same hazard as the Have
                # broadcast in _finish_piece)
            try:
                if p.ext.enabled and p.ext.lt_donthave_id:
                    await proto.send_message(
                        p.writer, proto.Extended(p.ext.lt_donthave_id, payload)
                    )
                await self._update_interest(p)
            except (ConnectionError, OSError):
                continue

    async def _serve_read_retry(self, make_read):
        """Serve-path read with ONE retry for transient failures.

        A momentary failure (fd exhaustion under connection fanout, EIO
        from a busy disk, an interrupted syscall) is not piece loss:
        treating it as permanent retracts the piece, demotes a seed to
        DOWNLOADING, and re-downloads from the swarm. Only an error that
        persists across the retry — or one that is structurally permanent
        (missing file, short read) — reaches the ``_piece_lost``
        self-heal path.
        """
        try:
            return await make_read()
        except StorageError as e:
            cause = e.__cause__
            # no OSError cause = the storage layer's own no-such-file /
            # short-read diagnosis: retrying cannot change the file's
            # length. ENOENT is likewise structural.
            if not isinstance(cause, OSError) or cause.errno == errno.ENOENT:
                raise
            log.warning("serve read transient error, retrying once: %s", e)
            await asyncio.sleep(0.05)
            return await make_read()

    async def _reactor_serve(self, key, item) -> None:
        """ReactorPool drain callback: resolve the peer (it may have
        left while the request queued) and serve. Connection-level
        failures tear the peer down here — the worker pool must survive
        any one peer's death."""
        peer = self.peers.get(key)
        if peer is None:
            return
        index, begin, length = item
        try:
            await self._serve_request(peer, index, begin, length)
        except (proto.ProtocolError, ConnectionError, OSError):
            # a torn frame (zero-copy mid-send failure) or a dead socket:
            # the stream is unusable — abort, don't let it desync
            transport = getattr(peer.writer, "transport", None)
            if transport is not None:
                try:
                    transport.abort()
                except Exception:
                    pass
            self._drop_peer(peer)

    async def _serve_zero_copy(self, peer: PeerConnection, index, begin, length) -> str | None:
        """Try the serve_plane egress engine: ``"sendfile"``/``"preadv"``
        when the span went out zero-copy(-ish), ``None`` when the caller
        must serve through the buffered piece-cache path. Only plaintext
        writers are eligible — MSE wraps every byte in RC4, so splicing
        raw file bytes past the cipher would corrupt the stream."""
        from torrent_tpu.net.mse import WrappedWriter

        if isinstance(peer.writer, WrappedWriter):
            return None
        offset = index * self.info.piece_length + begin
        if self._egress.classify(offset, length) is None:
            return None
        # the span is fd-backed and EOF-checked: debit the upload caps
        # now (the copy path debits after its read for the same reason —
        # a read that can still fail must not burn cap budget; here the
        # only failure mode left is the connection itself)
        if self.upload_bucket is not None and not self.upload_bucket.unlimited:
            await self.upload_bucket.take(length)
        if not self.own_upload_bucket.unlimited:
            await self.own_upload_bucket.take(length)
        t0 = time.monotonic()
        path = await self._egress.send_block(peer.writer, index, begin, length)
        if path is not None:
            self._egress_charge(time.monotonic() - t0, length)
        return path

    def _serve_done(self, peer: PeerConnection, length: int, path: str) -> None:
        """Common post-egress accounting: transfer counters, swarm +
        serve telemetry (the fallback matrix), and the DRR deficit
        spend that makes the choke economics bite."""
        peer.bytes_up += length
        self.uploaded += length
        peer.last_tx = time.monotonic()
        okey = self._obs_key(peer)
        self._swarm_obs.on_upload(okey, length)
        self._serve_obs.on_egress(okey, path, length)
        self._serve_econ.charge(peer.peer_id, length)

    async def _serve_request(self, peer: PeerConnection, index, begin, length) -> None:
        """request handler (torrent.ts:158-176), gated on our choke state.

        BEP 6 changes both gates: a choked fast peer may still fetch its
        allowed-fast pieces, and anything we won't serve is rejected
        explicitly instead of silently dropped.
        """
        if not validate_requested_block(self.info, index, begin, length):
            raise proto.ProtocolError("invalid request")

        async def refuse():
            # fast peers get an explicit reject; BEP 3 peers silent-drop
            if peer.fast:
                await proto.send_message(
                    peer.writer, proto.RejectRequest(index, begin, length)
                )

        if self.paused:
            # BEP 6 contract: anything we won't serve is rejected
            # explicitly (a request can race our pause-time Choke)
            await refuse()
            return
        if peer.am_choking and not (peer.fast and index in peer.allowed_fast_out):
            # the economics said no: count it, so a crowd hammering
            # through its choke shows up in the serve telemetry even
            # though BEP 3 peers get no wire-level answer
            self._serve_obs.on_reject(self._obs_key(peer), "choked")
            await refuse()
            return
        if not self.bitfield.has(index):
            await refuse()
            return
        if (
            self.super_seeding()
            and not peer.ss_exempt
            and index not in peer.ss_advertised
        ):
            # BEP 16: only revealed pieces are served — a peer asking for
            # something we never advertised is buggy or probing (peers
            # that saw the real bitfield before the mode flipped on are
            # exempt; refusing them would stall legitimate requests)
            await refuse()
            return
        # Zero-copy egress first (serve_plane/egress.py): an fs-backed
        # span that maps contiguously into one file skips the piece
        # cache entirely — header + kernel splice (or one pooled preadv)
        # instead of pread/slice/append. Anything ineligible (memory
        # backends, pad spans, file boundaries, MSE) falls through to
        # the buffered tiers below, which remain the universal path.
        zpath = await self._serve_zero_copy(peer, index, begin, length)
        if zpath is not None:
            self._serve_done(peer, length, zpath)
            return
        # Serve through a small LRU of whole pieces: peers request a
        # piece as ~16-64 sequential 16 KiB blocks, so reading the piece
        # once turns 16+ random preads into one. Concurrent misses on the
        # same piece share ONE read via _serve_pending; huge pieces skip
        # the cache (whole-piece reads would amplify one-block fetches).
        if self.info.piece_length > self.config.serve_cache_max_piece:
            try:
                block = await self._serve_read_retry(
                    lambda: asyncio.to_thread(
                        self.storage.get,
                        index * self.info.piece_length + begin,
                        length,
                    )
                )
            except StorageError as e:
                log.error("serving piece %d failed: %s", index, e)
                await self._piece_lost(index)
                await refuse()
                return
        elif self.info.piece_length <= INLINE_IO_MAX:
            # small pieces: a synchronous pread is cheaper than the
            # thread hop the whole-piece cache path would pay
            piece = self._serve_cache.pop(index, None)
            if piece is None:

                async def _read_small():
                    # stays on the event loop: a sync pread here is
                    # cheaper than the thread hop (see branch comment)
                    return self.storage.read_piece(index)

                try:
                    piece = await self._serve_read_retry(_read_small)
                except StorageError as e:
                    log.error("serving piece %d failed: %s", index, e)
                    await self._piece_lost(index)
                    await refuse()
                    return
            self._serve_cache[index] = piece  # insert/LRU-refresh at tail
            while len(self._serve_cache) > self.config.serve_cache_pieces:
                self._serve_cache.pop(next(iter(self._serve_cache)))
            block = piece[begin : begin + length]
        else:
            piece = self._serve_cache.get(index)
            if piece is None:

                def _shared_read():
                    # a retry lands AFTER the failed task's done-callback
                    # popped it, so it installs (or joins) a fresh one
                    task = self._serve_pending.get(index)
                    if task is None:
                        task = asyncio.ensure_future(
                            asyncio.to_thread(self.storage.read_piece, index)
                        )
                        self._serve_pending[index] = task
                        task.add_done_callback(
                            lambda _t, i=index: self._serve_pending.pop(i, None)
                        )
                    return asyncio.shield(task)

                try:
                    piece = await self._serve_read_retry(_shared_read)
                except StorageError as e:
                    log.error("serving piece %d failed: %s", index, e)
                    await self._piece_lost(index)
                    await refuse()
                    return
                self._serve_cache[index] = piece
                while len(self._serve_cache) > self.config.serve_cache_pieces:
                    self._serve_cache.pop(next(iter(self._serve_cache)))
            else:
                self._serve_cache.pop(index)  # LRU refresh: reinsert at tail
                self._serve_cache[index] = piece
            block = piece[begin : begin + length]
        if len(block) != length:
            log.error("serving piece %d: short read", index)
            return
        if self.upload_bucket is not None and not self.upload_bucket.unlimited:
            # client-global upload cap; debited only once the block read
            # succeeded so storage errors don't burn cap budget
            await self.upload_bucket.take(length)
        if not self.own_upload_bucket.unlimited:
            await self.own_upload_bucket.take(length)  # per-torrent layer
        t0 = time.monotonic()
        await proto.send_message(peer.writer, proto.Piece(index, begin, block))
        self._egress_charge(time.monotonic() - t0, length)
        self._serve_done(peer, length, "copy")

    # ---------------------------------------------------------- choke loop

    async def _release_snubbed(self) -> None:
        """Anti-snubbing: a peer that stopped delivering blocks while we
        have requests outstanding to it gets those requests cancelled and
        released, is flagged snubbed (no fresh requests outside endgame
        until it delivers again), and the freed blocks are immediately
        re-offered to every other ready peer. The connection survives —
        it still counts for availability and may serve later."""
        now = time.monotonic()
        released_any = False
        for p in list(self.peers.values()):  # awaits below; dict may mutate
            if p.pacing:
                continue  # queued in the download cap, not stalled
            if p.inflight and now - p.last_block_rx > self.config.snub_timeout:
                log.debug(
                    "peer %s snubbed: releasing %d in-flight blocks",
                    p.peer_id[:8].hex(),
                    len(p.inflight),
                )
                await self._cancel_and_release(p)
                # time-limited, not permanent: after the cooldown the peer
                # is retried even without having delivered (a transient
                # stall of EVERY peer must not deadlock the session)
                p.snubbed_until = now + 2 * self.config.snub_timeout
                self._swarm_obs.on_snub(self._obs_key(p))
                released_any = True
        if released_any:
            for p in list(self.peers.values()):
                if not p.snubbed and not p.peer_choking and p.am_interested:
                    try:
                        await self._fill_pipeline(p)
                    except (ConnectionError, OSError):
                        # a reset socket whose peer-loop hasn't noticed
                        # yet must not kill the CHOKE loop for the
                        # torrent's remaining lifetime
                        continue

    async def _choke_loop(self) -> None:
        """Unchoke by DRR deficit + one seeded optimistic slot (BEP 3
        semantics, serve_plane/choke.py economics).

        Leeching weighs candidates by download rate (tit-for-tat);
        seeding by upload rate (serve whoever drains us fastest). The
        rates feed :class:`ChokeEconomics` as DRR weights: deficits
        accrue per round, actual egress spends them (``_serve_done``),
        and a candidate that keeps losing keeps accruing — so the
        ranking preserves the old rate order while making starvation
        structurally impossible. Round duration, slot occupancy, and
        optimistic rotation land in the serve telemetry."""
        econ = self._serve_econ
        while not self._stopping:
            await asyncio.sleep(self.config.choke_interval)
            if self.paused:
                continue  # pause() choked everyone; stay that way
            t0 = time.monotonic()
            await self._release_snubbed()
            peers = list(self.peers.values())
            interested = [p for p in peers if p.peer_interested]
            seeding = self.state == TorrentState.SEEDING
            rates = {
                p.peer_id: (p.upload_rate() if seeding else p.download_rate())
                for p in interested
            }
            # normalize to DRR weights: the fastest reciprocator accrues
            # a full quantum per round, the rest proportionally (with
            # the economics' floor so newcomers accrue too)
            top = max(rates.values(), default=0.0)
            econ.slots = max(0, self.config.unchoke_slots)
            verdict = econ.round(
                {pid: (r / top if top > 0 else 0.0) for pid, r in rates.items()}
            )
            unchoke_ids = set(verdict.all_unchoked())
            for p in peers:
                should_unchoke = p.peer_id in unchoke_ids
                try:
                    if should_unchoke and p.am_choking:
                        p.am_choking = False
                        self._swarm_obs.on_state(self._obs_key(p), am_choking=False)
                        await proto.send_message(p.writer, proto.Unchoke())
                    elif not should_unchoke and not p.am_choking:
                        p.am_choking = True
                        self._swarm_obs.on_state(self._obs_key(p), am_choking=True)
                        await proto.send_message(p.writer, proto.Choke())
                except (ConnectionError, OSError):
                    pass
                p.snapshot_rate()
            opt_peer = (
                self.peers.get(verdict.optimistic)
                if verdict.optimistic is not None
                else None
            )
            self._serve_obs.on_choke_round(
                time.monotonic() - t0,
                unchoked=len(verdict.unchoked),
                interested=len(interested),
                optimistic=self._obs_key(opt_peer) if opt_peer else None,
                rotated=verdict.rotated,
            )

    def _dialable_addr(self, p: PeerConnection) -> tuple[str, int] | None:
        """The address other peers could actually connect to.

        Outbound connections dialed the peer's listen port; inbound ones
        carry an ephemeral source port, so they're only gossipable when
        the peer advertised its real port via BEP 10's ``p`` key. Both
        families gossip — encode_pex routes v4 to added/dropped and v6
        to added6/dropped6 (BEP 11).
        """
        if p.address is None:
            return None
        # dual-stack listeners report v4 peers as ::ffff:a.b.c.d —
        # collapse so the compact packers route them to the v4 field
        from torrent_tpu.net.types import normalize_peer_host

        host = normalize_peer_host(p.address[0])
        if not p.inbound:
            return (host, p.address[1])
        if p.ext.listen_port:
            return (host, p.ext.listen_port)
        return None

    async def _pex_round(self) -> None:
        """Send each PEX-capable peer the delta of connected addresses."""
        current = {
            addr
            for p in self.peers.values()
            if (addr := self._dialable_addr(p)) is not None
        }
        for p in list(self.peers.values()):
            if not (p.ext.enabled and p.ext.ut_pex_id):
                continue
            mine = self._dialable_addr(p)
            added = current - p.pex_sent - ({mine} if mine else set())
            dropped = p.pex_sent - current
            if not added and not dropped:
                continue
            try:
                await proto.send_message(
                    p.writer,
                    proto.Extended(p.ext.ut_pex_id, ext.encode_pex(added, dropped)),
                )
            except (ConnectionError, OSError):
                continue
            p.pex_sent = (p.pex_sent | added) - dropped

    async def _pex_loop(self) -> None:
        while not self._stopping:
            await asyncio.sleep(self.config.pex_interval)
            await self._pex_round()

    # ------------------------------------------------------------ webseeds

    def _pick_webseed_pieces(self, n: int) -> list[int]:
        """Missing pieces nobody is working on, stream windows first,
        then rarest (in the swarm) — the webseed complements peers
        instead of racing them.

        A STALE partial (blocks received but none in flight — typically
        a resumed checkpoint with no peer holding the piece) is fair
        game: without this, a webseed-only session could never finish a
        resumed partial and would sit short of completion forever. The
        HTTP fetch re-downloads the whole piece; the reserve/handback
        logic in the loop already covers racing late wire blocks.
        """
        if self._rarity_dirty:
            self._rebuild_rarity()
        # per-piece mirror answers this in O(pieces-with-requests); the
        # old per-block Counter walk grew to every block key ever
        # requested over a download (entries never prune at zero)
        busy = {i for i, c in self._piece_inflight.items() if c > 0}
        picked = []

        def eligible(index: int) -> bool:
            if self.bitfield.has(index) or index in busy:
                return False
            if self._piece_priority[index] <= 0:
                return False
            p = self._partials.get(index)
            if p is not None and (p.webseed or not p.received):
                return False  # reserved by another webseed loop
            return True

        # stream readers are latency-bound on exactly these pieces — the
        # same priority the wire picker gives them (the delta-path window
        # advance never rebuilds the rarity order, so consult directly)
        for first, count in sorted(self._stream_positions.values()):
            for index in range(first, min(first + count, self.info.num_pieces)):
                if eligible(index) and index not in picked:
                    picked.append(index)
                    if len(picked) >= n:
                        return picked
        for index in self._rarity_order:
            if eligible(index) and index not in picked:
                picked.append(index)
                if len(picked) >= n:
                    break
        return picked

    def _spawn_seed_loops(self) -> None:
        """Start one fetch loop per BEP 19 webseed and BEP 17 httpseed.

        Re-entrant: callers re-open a finished download (selection
        widening, BEP 54 piece loss) without knowing whether the old
        loops already exited — a URL whose loop is still alive (mid-fetch
        or in a backoff sleep when the re-open happened) is skipped, or
        every lost/heal cycle would stack another loop per URL.
        """
        for url in self.web_seed_urls:
            self._spawn_seed_loop_once(url, bep17=False)
        for url in self.http_seed_urls:
            self._spawn_seed_loop_once(url, bep17=True)

    def _spawn_seed_loop_once(self, url: str, bep17: bool) -> None:
        key = ("h" if bep17 else "w") + url
        task = self._seed_loop_tasks.get(key)
        if task is not None and not task.done():
            return
        self._seed_loop_tasks[key] = self._spawn(
            self._webseed_loop(url, bep17=bep17),
            name=f"{'httpseed' if bep17 else 'webseed'}-{url[:24]}",
        )

    async def _webseed_loop(self, url: str, bep17: bool = False) -> None:
        """BEP 19 (byte-range) / BEP 17 (piece-keyed) HTTP seeding: fill
        missing pieces from an HTTP seed; every fetched piece passes the
        same verify→persist→have path as wire pieces.

        A webseed serving corrupt data has no wire contributors for the
        strike system to ban, so the loop tracks consecutive hash
        failures itself: backoff per failure, URL disabled at the
        configured threshold (a hot refetch loop otherwise).
        """
        from torrent_tpu.session.webseed import (
            WebSeedError,
            fetch_piece,
            fetch_piece_bep17,
        )

        if bep17:
            def fetch(index: int) -> bytes:
                return fetch_piece_bep17(url, self.metainfo.info_hash, self.info, index)
        else:
            def fetch(index: int) -> bytes:
                return fetch_piece(url, self.storage, self.info, index)

        consecutive_failures = 0
        while not self._stopping and self._wanted_remaining():
            if self.paused:
                await asyncio.sleep(1.0)
                continue
            picked = self._pick_webseed_pieces(self.config.webseed_concurrency)
            if not picked:
                await asyncio.sleep(1.0)
                continue
            # reserve so peers/other webseeds skip these pieces meanwhile
            reserved = []
            for index in picked:
                existing = self._partials.get(index)
                if existing is not None:
                    # ADOPT a stale wire partial in place (resumed, or a
                    # dropped peer's leftovers): its received blocks and
                    # their downloaded-bytes accounting survive — on
                    # failure the handback returns them to the block
                    # scheduler, on success `already` subtracts them
                    existing.webseed = True
                    reserved.append(existing)
                    continue
                partial = _PartialPiece(
                    index=index,
                    length=piece_length(self.info, index),
                    buffer=bytearray(piece_length(self.info, index)),
                    webseed=True,
                )
                self._partials[index] = partial
                reserved.append(partial)
            try:
                datas = await asyncio.gather(
                    *(asyncio.to_thread(fetch, p.index) for p in reserved)
                )
            except WebSeedError as e:
                for p in reserved:
                    if self._partials.get(p.index) is p:
                        if p.received:
                            # endgame peers delivered blocks meanwhile —
                            # hand the partial (and their progress) back
                            # to the block scheduler instead of discarding
                            p.webseed = False
                        else:
                            del self._partials[p.index]
                log.warning("webseed %s failed: %s; backing off", url, e)
                await asyncio.sleep(self.config.webseed_retry)
                continue
            hash_failures = 0
            for partial, data in zip(reserved, datas):
                if self._partials.get(partial.index) is not partial:
                    # An endgame peer completed this piece while the HTTP
                    # fetch was in flight — its _finish_piece already ran;
                    # finishing ours too would double-count stats.
                    continue
                # Count only bytes the webseed actually contributed (endgame
                # peers may have delivered blocks that ingest already
                # counted), and clear those peers from the blame set — the
                # buffer is now entirely the webseed's bytes, so a corrupt
                # fetch must not strike innocent wire contributors.
                already = sum(
                    min(BLOCK_SIZE, partial.length - off) for off in partial.received
                )
                partial.buffer[:] = data
                partial.contributors.clear()
                partial.received = set(range(0, partial.length, BLOCK_SIZE))
                self.downloaded += partial.length - already
                outcome = await self._finish_piece(partial)
                if outcome == "corrupt":
                    hash_failures += 1
            if hash_failures:
                consecutive_failures += hash_failures
                if consecutive_failures >= self.config.webseed_max_failures:
                    log.error(
                        "webseed %s served %d corrupt pieces; disabling",
                        url,
                        consecutive_failures,
                    )
                    return
                log.warning(
                    "webseed %s served %d corrupt piece(s); backing off",
                    url,
                    hash_failures,
                )
                await asyncio.sleep(self.config.webseed_retry)
            else:
                consecutive_failures = 0

    async def _keepalive_loop(self) -> None:
        while not self._stopping:
            await asyncio.sleep(self.config.keepalive_interval)
            for p in list(self.peers.values()):
                try:
                    await proto.send_message(p.writer, proto.KeepAlive())
                except (ConnectionError, OSError):
                    self._drop_peer(p)

    async def _idle_sweep_loop(self) -> None:
        """Drop peers silent past ``peer_timeout`` (the per-message
        ``wait_for`` this replaces — see _peer_loop), with the
        which-slot-is-dead decision delegated to :class:`AcceptGate`.

        Teardown must be unconditional: a graceful ``close()`` waits for
        the transport's send buffer to drain, and a dead peer that
        stopped ACKing mid-upload never drains it — ``connection_lost``
        (and so the peer loop's EOF) would wait on the kernel's TCP
        retransmission timeout. So the sweep aborts the transport when
        one is exposed (TCP/MSE; discards the buffer, fires
        connection_lost now) and does the ``_drop_peer`` bookkeeping
        itself — idempotent against the loop's ``finally`` re-drop. uTP
        writers expose no transport; their ``close()`` FIN path is
        bounded by MAX_RETRANSMITS on its own. Worst-case drop time is
        ``timeout + interval`` (1.25x at the default 240 s timeout; the
        interval floors at 1 s for very short timeouts)."""
        interval = max(1.0, self.config.peer_timeout / 4)
        while not self._stopping:
            await asyncio.sleep(interval)
            # the AcceptGate owns the idle-eviction decision (and its
            # evicted_idle counter — the same object the scenario
            # plane's slowloris suite attacks); rx activity is synced
            # here rather than on every message, which is equivalent at
            # sweep granularity
            now = time.monotonic()
            for p in self.peers.values():
                self._accept_gate.touch(p.peer_id, p.last_rx)
            evicted = self._accept_gate.sweep(now)
            self._serve_obs.on_gate_evictions(len(evicted))
            for peer_id in evicted:
                p = self.peers.get(peer_id)
                if p is None:
                    continue
                log.debug("peer %r idle past timeout — dropping", p.peer_id[:8])
                transport = getattr(p.writer, "transport", None)
                if transport is not None:
                    try:
                        transport.abort()
                    except Exception:
                        pass
                self._drop_peer(p)

    # ------------------------------------------------------------- status

    def _count_encrypted_peers(self) -> int:
        from torrent_tpu.net.mse import WrappedWriter

        return sum(
            1 for p in self.peers.values() if isinstance(p.writer, WrappedWriter)
        )

    def status(self) -> dict:
        return {
            "state": self.state.value,
            "pieces": f"{self.bitfield.count()}/{self.info.num_pieces}",
            "peers": len(self.peers),
            "idle_evicted": self._accept_gate.evicted_idle,
            "downloaded": self.downloaded,
            "uploaded": self.uploaded,
            "left": self.left,
            "endgame": self._endgame,
            "paused": self.paused,
            "super_seeding": self.super_seeding(),
            "wanted_left": self._wanted_missing,
            "sequential": self.config.sequential,
            "download_rate": round(
                sum(p.download_rate() for p in self.peers.values()), 1
            ),
            "encryption": self.config.encryption,
            "encrypted_peers": self._count_encrypted_peers(),
            "stream_readers": len(self._stream_positions),
            "partials": len(self._partials),
            "max_upload_bps": self.config.max_upload_bps,
            "max_download_bps": self.config.max_download_bps,
            "serve": {
                "reactor_running": self._serve_reactor.running,
                "queued": sum(
                    self._serve_reactor.depth(pid) for pid in self.peers
                ),
                "rejected_backpressure": self._serve_reactor.rejected,
                "rejected_per_ip": self._accept_gate.rejected_per_ip,
                "choke_rounds": self._serve_econ.rounds,
                "optimistic_rotations": self._serve_econ.rotations,
                "egress_paths": dict(self._egress.served),
            },
        }
