from torrent_tpu.session.peer import PeerConnection
from torrent_tpu.session.torrent import Torrent, TorrentState
from torrent_tpu.session.client import Client, ClientConfig

__all__ = ["PeerConnection", "Torrent", "TorrentState", "Client", "ClientConfig"]
