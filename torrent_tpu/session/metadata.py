"""Magnet join: fetch the info dict from the swarm via BEP 9 ut_metadata.

The reference lists magnet links as roadmap (README.md:39). This driver
completes the path: announce with just the magnet's info hash, dial
peers, negotiate BEP 10, pull metadata pieces, SHA1-verify the assembled
blob against the info hash, and return a full ``Metainfo`` ready for
``Client.add``.

Peers are tried concurrently and independently — each attempt fetches
the whole (typically few-KiB) dict, and the first complete verified copy
wins; losers are cancelled. ``max_concurrent`` bounds the redundant
bandwidth. Within a peer, piece requests are pipelined.
"""

from __future__ import annotations

import asyncio

from torrent_tpu.codec.magnet import Magnet
from torrent_tpu.codec.metainfo import Metainfo, metainfo_from_info_bytes
from torrent_tpu.net import extension as ext
from torrent_tpu.net import protocol as proto
from torrent_tpu.net.types import AnnounceEvent, AnnounceInfo
from torrent_tpu.utils.log import get_logger

log = get_logger("session.metadata")


class MetadataError(Exception):
    pass


async def _fetch_layers_on_conn(
    reader, writer, info_v2, timeout: float
) -> dict[bytes, tuple[bytes, ...]]:
    """Pull every multi-piece file's piece layer over an already-open
    peer connection (BEP 52 messages 21-23), each run proven against the
    file's trusted ``pieces root`` before acceptance.

    A btmh magnet joiner needs this immediately after ut_metadata: the
    info dict carries only per-file roots; the per-piece expected digests
    (the piece layers) live outside it. Reuses the metadata connection —
    the peer that served the info dict is the one best placed to serve
    the layers, and no session object exists yet to route futures.
    """
    from torrent_tpu.models.hashes import (
        MAX_RUN,
        HashRequestFields,
        _layer_height,
        verify_hash_response,
    )
    from torrent_tpu.session.v2 import multi_piece_roots

    plen = info_v2.piece_length
    base = _layer_height(plen)
    layers: dict[bytes, tuple[bytes, ...]] = {}
    for root, n_pieces in multi_piece_roots(info_v2):
        padded = 1 << (n_pieces - 1).bit_length()
        run = min(padded, MAX_RUN)
        # runs beyond MAX_RUN chain to the root via uncle proofs
        proofs = (padded.bit_length() - 1) - (run.bit_length() - 1)
        got_all: list[bytes] = []
        for start in range(0, min(padded, n_pieces), run):
            fields = (root, base, start, run, proofs)
            req = HashRequestFields(*fields)
            writer.write(proto.encode_message(proto.HashRequest(*fields)))
            await writer.drain()
            while True:
                msg = await asyncio.wait_for(proto.read_message(reader), timeout=timeout)
                if msg is None:
                    raise MetadataError("peer closed during layer fetch")
                if isinstance(msg, (proto.Hashes, proto.HashReject)) and (
                    msg.pieces_root,
                    msg.base_layer,
                    msg.index,
                    msg.length,
                    msg.proof_layers,
                ) == fields:
                    if isinstance(msg, proto.HashReject):
                        raise MetadataError("peer rejected piece-layer request")
                    got = msg.hash_list()
                    break
                # bitfield/have/choke etc. — irrelevant, keep draining
            if not verify_hash_response(req, got):
                raise MetadataError("piece-layer response failed merkle proof")
            got_all.extend(got[:run])
        layers[root] = tuple(got_all[:n_pieces])
    return layers


async def _fetch_from_peer(
    addr: tuple[str, int],
    info_hash: bytes,
    peer_id: bytes,
    timeout: float,
    v2_hash: bytes | None = None,
    proxy=None,
) -> tuple[bytes, dict | None]:
    """Dial one peer and pull the whole info dict from it.

    ``v2_hash`` switches validation to BEP 52 (SHA-256 of the blob must
    equal the btmh topic) and additionally fetches the piece layers on
    the same connection → ``(blob, layers)``; v1 returns ``(blob, None)``.
    """
    if proxy is not None:
        from torrent_tpu.net.socks import open_connection as socks_open

        reader, writer = await asyncio.wait_for(
            socks_open(proxy, addr[0], addr[1]), timeout=timeout * 2
        )
    else:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(addr[0], addr[1]), timeout=timeout
        )
    try:
        await proto.send_handshake(writer, info_hash, peer_id, ext.extension_reserved())
        ih, reserved = await asyncio.wait_for(proto.read_handshake_head(reader), timeout=timeout)
        await asyncio.wait_for(proto.read_handshake_peer_id(reader), timeout=timeout)
        if ih != info_hash:
            raise MetadataError("handshake info hash mismatch")
        if not ext.supports_extensions(reserved):
            raise MetadataError("peer has no extension protocol")
        state = ext.ExtensionState(enabled=True)
        writer.write(proto.encode_message(proto.Extended(0, ext.encode_extended_handshake())))
        await writer.drain()

        assembler: ext.MetadataAssembler | None = None
        deadline = asyncio.get_running_loop().time() + timeout * 10

        while True:
            if asyncio.get_running_loop().time() > deadline:
                raise MetadataError("metadata fetch deadline exceeded")
            msg = await asyncio.wait_for(proto.read_message(reader), timeout=timeout)
            if msg is None:
                raise MetadataError("peer closed during metadata fetch")
            if not isinstance(msg, proto.Extended):
                continue  # bitfield / have etc. — irrelevant pre-metadata
            if msg.ext_id == 0:
                ext.decode_extended_handshake(msg.payload, state)
                if state.ut_metadata_id == 0 or state.metadata_size == 0:
                    raise MetadataError("peer does not serve ut_metadata")
                if assembler is not None:
                    continue  # BEP 10 allows repeat handshakes; keep progress
                assembler = ext.MetadataAssembler(state.metadata_size)
                for piece in assembler.missing():
                    writer.write(
                        proto.encode_message(
                            proto.Extended(
                                state.ut_metadata_id, ext.encode_metadata_request(piece)
                            )
                        )
                    )
                await writer.drain()
                continue
            if msg.ext_id != ext.LOCAL_EXT_IDS[ext.UT_METADATA] or assembler is None:
                continue
            mm = ext.decode_metadata_message(msg.payload)
            if mm is None:
                continue
            if mm.msg_type == ext.MsgType.REJECT:
                raise MetadataError(f"peer rejected metadata piece {mm.piece}")
            if mm.msg_type == ext.MsgType.DATA:
                assembler.add(mm)
                if not assembler.complete:
                    continue
                if v2_hash is None:
                    blob = assembler.result(info_hash)
                    if blob is None:
                        raise MetadataError("metadata failed hash verification")
                    return blob, None
                blob = assembler.result_v2(v2_hash)
                if blob is None:
                    raise MetadataError("metadata failed sha-256 verification")
                from torrent_tpu.codec.bencode import BencodeError, bdecode
                from torrent_tpu.codec.metainfo_v2 import parse_v2_info_dict

                # a btmh topic minted from a non-bencode blob passes the
                # sha-256 check; the decode failure must stay a
                # MetadataError so other candidate peers are still tried
                try:
                    info_v2 = parse_v2_info_dict(bdecode(blob, strict=False))
                except BencodeError as e:
                    raise MetadataError(f"fetched v2 info dict not bencode: {e}")
                if info_v2 is None:
                    raise MetadataError("fetched v2 info dict failed validation")
                layers = await _fetch_layers_on_conn(reader, writer, info_v2, timeout)
                return blob, layers
    finally:
        writer.close()


async def fetch_metadata(
    magnet: Magnet,
    peer_id: bytes,
    port: int = 6881,
    peer_timeout: float = 10.0,
    max_concurrent: int = 8,
    dht=None,
    ip_filter=None,  # optional net.ipfilter.IpFilter: candidates never dialed
    proxy=None,  # optional net.socks.ProxySpec for peer dials + trackers
) -> "Metainfo":
    """Resolve a magnet to a full session metainfo using trackers + x.pe
    peers + (when a ``net.dht.DHTNode`` is supplied) mainline-DHT
    discovery.

    v1/hybrid magnets (btih) return a ``Metainfo``; pure-v2 magnets
    (btmh only) fetch the info dict AND the piece layers (BEP 52 hash
    transfer) and return a ``session.v2.V2SessionMeta``. Either result
    drops straight into ``Client.add``. Raises ``MetadataError`` if no
    reachable peer can serve a verified copy.
    """
    v2_only = magnet.info_hash is None
    # BEP 52: a pure-v2 swarm announces and handshakes with the
    # TRUNCATED sha-256 infohash (the v2 analogue of protocol.ts:36-67)
    wire_hash = magnet.wire_hash
    candidates: list[tuple[str, int]] = list(magnet.peer_addrs)
    if dht is not None:
        try:
            candidates.extend(await dht.lookup_peers(wire_hash))
        except Exception as e:
            log.warning("dht peer lookup failed: %s", e)
    if magnet.trackers:
        from torrent_tpu.net.tracker import TrackerError, announce

        info = AnnounceInfo(
            info_hash=wire_hash,
            peer_id=peer_id,
            port=port,
            uploaded=0,
            downloaded=0,
            left=1,  # unknown size: nonzero = we're a leecher
            event=AnnounceEvent.STARTED,
        )
        for tr in magnet.trackers:
            try:
                res = await announce(tr, info, proxy=proxy)
                candidates.extend((p.ip, p.port) for p in res.peers)
            except (TrackerError, OSError, asyncio.TimeoutError) as e:
                log.warning("magnet announce to %s failed: %s", tr, e)
    seen: set[tuple[str, int]] = set()
    candidates = [c for c in candidates if not (c in seen or seen.add(c))]
    if ip_filter is not None:
        # the blocklist covers the metadata fetch too — "never dialed"
        # must hold before the torrent object even exists
        candidates = [c for c in candidates if not ip_filter.blocked(c[0])]
    if not candidates:
        raise MetadataError("magnet has no reachable peer sources")

    sem = asyncio.Semaphore(max_concurrent)
    errors: list[str] = []

    async def attempt(addr):
        async with sem:
            try:
                return await _fetch_from_peer(
                    addr,
                    wire_hash,
                    peer_id,
                    peer_timeout,
                    v2_hash=magnet.info_hash_v2 if v2_only else None,
                    proxy=proxy,
                )
            except (MetadataError, proto.ProtocolError, OSError, asyncio.TimeoutError) as e:
                errors.append(f"{addr}: {e}")
                return None

    tasks = [asyncio.ensure_future(attempt(a)) for a in candidates]
    got = None
    try:
        for fut in asyncio.as_completed(tasks):
            got = await fut
            if got is not None:
                break
    finally:
        for t in tasks:
            t.cancel()
    if got is None:
        raise MetadataError(f"all metadata sources failed: {errors[:5]}")
    blob, layers = got
    if v2_only:
        from torrent_tpu.session.v2 import V2Error, v2_session_meta_from_parts

        try:
            return v2_session_meta_from_parts(
                blob,
                magnet.info_hash_v2,
                layers or {},
                announce=magnet.trackers[0] if magnet.trackers else "",
            )
        except V2Error as e:
            raise MetadataError(f"fetched v2 metadata unusable: {e}")
    mi = metainfo_from_info_bytes(
        blob,
        announce=magnet.trackers[0] if magnet.trackers else "",
        announce_list=[[t] for t in magnet.trackers] if magnet.trackers else None,
    )
    if mi is None:
        raise MetadataError("fetched info dict failed metainfo validation")
    if mi.info_hash != magnet.info_hash:
        # A dict that doesn't re-encode byte-exactly (e.g. duplicate keys)
        # would otherwise be registered/announced under the wrong hash.
        raise MetadataError("info dict does not round-trip to the magnet hash")
    return mi
