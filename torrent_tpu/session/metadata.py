"""Magnet join: fetch the info dict from the swarm via BEP 9 ut_metadata.

The reference lists magnet links as roadmap (README.md:39). This driver
completes the path: announce with just the magnet's info hash, dial
peers, negotiate BEP 10, pull metadata pieces, SHA1-verify the assembled
blob against the info hash, and return a full ``Metainfo`` ready for
``Client.add``.

Peers are tried concurrently and independently — each attempt fetches
the whole (typically few-KiB) dict, and the first complete verified copy
wins; losers are cancelled. ``max_concurrent`` bounds the redundant
bandwidth. Within a peer, piece requests are pipelined.
"""

from __future__ import annotations

import asyncio

from torrent_tpu.codec.magnet import Magnet
from torrent_tpu.codec.metainfo import Metainfo, metainfo_from_info_bytes
from torrent_tpu.net import extension as ext
from torrent_tpu.net import protocol as proto
from torrent_tpu.net.types import AnnounceEvent, AnnounceInfo
from torrent_tpu.utils.log import get_logger

log = get_logger("session.metadata")


class MetadataError(Exception):
    pass


async def _fetch_from_peer(
    addr: tuple[str, int], info_hash: bytes, peer_id: bytes, timeout: float
) -> bytes:
    """Dial one peer and pull the whole info dict from it."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(addr[0], addr[1]), timeout=timeout
    )
    try:
        await proto.send_handshake(writer, info_hash, peer_id, ext.extension_reserved())
        ih, reserved = await asyncio.wait_for(proto.read_handshake_head(reader), timeout=timeout)
        await asyncio.wait_for(proto.read_handshake_peer_id(reader), timeout=timeout)
        if ih != info_hash:
            raise MetadataError("handshake info hash mismatch")
        if not ext.supports_extensions(reserved):
            raise MetadataError("peer has no extension protocol")
        state = ext.ExtensionState(enabled=True)
        writer.write(proto.encode_message(proto.Extended(0, ext.encode_extended_handshake())))
        await writer.drain()

        assembler: ext.MetadataAssembler | None = None
        deadline = asyncio.get_running_loop().time() + timeout * 10

        while True:
            if asyncio.get_running_loop().time() > deadline:
                raise MetadataError("metadata fetch deadline exceeded")
            msg = await asyncio.wait_for(proto.read_message(reader), timeout=timeout)
            if msg is None:
                raise MetadataError("peer closed during metadata fetch")
            if not isinstance(msg, proto.Extended):
                continue  # bitfield / have etc. — irrelevant pre-metadata
            if msg.ext_id == 0:
                ext.decode_extended_handshake(msg.payload, state)
                if state.ut_metadata_id == 0 or state.metadata_size == 0:
                    raise MetadataError("peer does not serve ut_metadata")
                if assembler is not None:
                    continue  # BEP 10 allows repeat handshakes; keep progress
                assembler = ext.MetadataAssembler(state.metadata_size)
                for piece in assembler.missing():
                    writer.write(
                        proto.encode_message(
                            proto.Extended(
                                state.ut_metadata_id, ext.encode_metadata_request(piece)
                            )
                        )
                    )
                await writer.drain()
                continue
            if msg.ext_id != ext.LOCAL_EXT_IDS[ext.UT_METADATA] or assembler is None:
                continue
            mm = ext.decode_metadata_message(msg.payload)
            if mm is None:
                continue
            if mm.msg_type == ext.MsgType.REJECT:
                raise MetadataError(f"peer rejected metadata piece {mm.piece}")
            if mm.msg_type == ext.MsgType.DATA:
                assembler.add(mm)
                if assembler.complete:
                    blob = assembler.result(info_hash)
                    if blob is None:
                        raise MetadataError("metadata failed hash verification")
                    return blob
    finally:
        writer.close()


async def fetch_metadata(
    magnet: Magnet,
    peer_id: bytes,
    port: int = 6881,
    peer_timeout: float = 10.0,
    max_concurrent: int = 8,
    dht=None,
    ip_filter=None,  # optional net.ipfilter.IpFilter: candidates never dialed
) -> Metainfo:
    """Resolve a magnet to a full ``Metainfo`` using trackers + x.pe peers
    + (when a ``net.dht.DHTNode`` is supplied) mainline-DHT discovery.

    Raises ``MetadataError`` if no reachable peer can serve a verified
    info dict.
    """
    candidates: list[tuple[str, int]] = list(magnet.peer_addrs)
    if dht is not None:
        try:
            candidates.extend(await dht.lookup_peers(magnet.info_hash))
        except Exception as e:
            log.warning("dht peer lookup failed: %s", e)
    if magnet.trackers:
        from torrent_tpu.net.tracker import TrackerError, announce

        info = AnnounceInfo(
            info_hash=magnet.info_hash,
            peer_id=peer_id,
            port=port,
            uploaded=0,
            downloaded=0,
            left=1,  # unknown size: nonzero = we're a leecher
            event=AnnounceEvent.STARTED,
        )
        for tr in magnet.trackers:
            try:
                res = await announce(tr, info)
                candidates.extend((p.ip, p.port) for p in res.peers)
            except (TrackerError, OSError, asyncio.TimeoutError) as e:
                log.warning("magnet announce to %s failed: %s", tr, e)
    seen: set[tuple[str, int]] = set()
    candidates = [c for c in candidates if not (c in seen or seen.add(c))]
    if ip_filter is not None:
        # the blocklist covers the metadata fetch too — "never dialed"
        # must hold before the torrent object even exists
        candidates = [c for c in candidates if not ip_filter.blocked(c[0])]
    if not candidates:
        raise MetadataError("magnet has no reachable peer sources")

    sem = asyncio.Semaphore(max_concurrent)
    errors: list[str] = []

    async def attempt(addr) -> bytes | None:
        async with sem:
            try:
                return await _fetch_from_peer(addr, magnet.info_hash, peer_id, peer_timeout)
            except (MetadataError, proto.ProtocolError, OSError, asyncio.TimeoutError) as e:
                errors.append(f"{addr}: {e}")
                return None

    tasks = [asyncio.ensure_future(attempt(a)) for a in candidates]
    blob: bytes | None = None
    try:
        for fut in asyncio.as_completed(tasks):
            blob = await fut
            if blob is not None:
                break
    finally:
        for t in tasks:
            t.cancel()
    if blob is None:
        raise MetadataError(f"all metadata sources failed: {errors[:5]}")
    mi = metainfo_from_info_bytes(
        blob,
        announce=magnet.trackers[0] if magnet.trackers else "",
        announce_list=[[t] for t in magnet.trackers] if magnet.trackers else None,
    )
    if mi is None:
        raise MetadataError("fetched info dict failed metainfo validation")
    if mi.info_hash != magnet.info_hash:
        # A dict that doesn't re-encode byte-exactly (e.g. duplicate keys)
        # would otherwise be registered/announced under the wrong hash.
        raise MetadataError("info dict does not round-trip to the magnet hash")
    return mi
