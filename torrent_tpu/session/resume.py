"""Fastresume: checkpoint/resume of session state (SURVEY §5 gap).

The reference lists "Resumption of torrent" as unchecked roadmap
(README.md:34); its only substrate is the bitfield + StorageMethod.exists.
Here resume is two complementary paths:

1. **Fastresume file** (this module): a bencoded sidecar checkpoint of
   the bitfield + transfer counters, saved on stop/progress and loaded
   on start — O(1) resume for cleanly-stopped sessions.
2. **Full recheck** (parallel/verify.py): hash everything on the cpu|tpu
   plane — the trustless path for missing/stale checkpoints, and the
   BASELINE north-star workload.

A loaded checkpoint is cross-checked against file sizes; any mismatch
falls back to the full recheck, so a lying checkpoint can't corrupt the
swarm (we'd serve bad pieces and get banned — worse than rechecking).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from torrent_tpu.codec.bencode import BencodeError, bdecode, bencode
from torrent_tpu.utils.bitfield import Bitfield

FORMAT_VERSION = 1


# partial-piece persistence caps: the resume file must stay small and a
# hostile checkpoint must not balloon memory
MAX_SAVED_PARTIALS = 64


@dataclass
class ResumeData:
    info_hash: bytes
    num_pieces: int
    bitfield: bytes
    uploaded: int = 0
    downloaded: int = 0
    # in-flight pieces at checkpoint time: piece index -> (block bitmap
    # LSB-first, buffer with received spans filled). Restart re-ingests
    # them so up to piece_length per partial isn't re-downloaded;
    # verification still gates persistence when the piece completes.
    partials: dict = field(default_factory=dict)
    # BEP 3 `completed` bookkeeping across restarts: ``completed_reported``
    # latches that the event was ever queued (a piece lost via BEP 54 and
    # re-fetched later must not announce a second completion);
    # ``completed_owed`` survives a crash between queuing the event and
    # the tracker actually receiving it, so the restarted session still
    # delivers the snatch.
    completed_reported: bool = False
    completed_owed: bool = False

    def encode(self) -> bytes:
        top = {
            b"version": FORMAT_VERSION,
            b"info_hash": self.info_hash,
            b"num_pieces": self.num_pieces,
            b"bitfield": self.bitfield,
            b"uploaded": self.uploaded,
            b"downloaded": self.downloaded,
        }
        if self.completed_reported:
            top[b"completed"] = 1
        if self.completed_owed:
            top[b"completed_owed"] = 1
        if self.partials:
            top[b"partials"] = {
                str(i).encode(): {b"mask": mask, b"data": data}
                for i, (mask, data) in sorted(self.partials.items())[
                    :MAX_SAVED_PARTIALS
                ]  # the single cap point (bounds file size + decode memory)
            }
        return bencode(top)

    @classmethod
    def decode(cls, raw: bytes) -> "ResumeData | None":
        try:
            d = bdecode(raw)
        except BencodeError:
            return None
        if not isinstance(d, dict) or d.get(b"version") != FORMAT_VERSION:
            return None
        partials: dict = {}
        saved = d.get(b"partials")
        if isinstance(saved, dict):
            for key, ent in list(saved.items())[:MAX_SAVED_PARTIALS]:
                if not (
                    isinstance(key, bytes)
                    and key.isdigit()
                    and isinstance(ent, dict)
                    and isinstance(ent.get(b"mask"), bytes)
                    and isinstance(ent.get(b"data"), bytes)
                ):
                    return None  # corrupt partial section → full recheck
                partials[int(key)] = (ent[b"mask"], ent[b"data"])
        try:
            rd = cls(
                info_hash=d[b"info_hash"],
                num_pieces=d[b"num_pieces"],
                bitfield=d[b"bitfield"],
                uploaded=d[b"uploaded"],
                downloaded=d[b"downloaded"],
                partials=partials,
                completed_reported=d.get(b"completed", 0) == 1,
                completed_owed=d.get(b"completed_owed", 0) == 1,
            )
        except KeyError:
            return None
        # Field types are attacker-controlled (bdecode gives int|bytes|...);
        # any type confusion means a corrupt checkpoint → full recheck.
        if not (
            isinstance(rd.info_hash, bytes)
            and isinstance(rd.num_pieces, int)
            and isinstance(rd.bitfield, bytes)
            and isinstance(rd.uploaded, int)
            and isinstance(rd.downloaded, int)
        ):
            return None
        if len(rd.info_hash) != 20 or rd.num_pieces < 0:
            return None
        if rd.uploaded < 0 or rd.downloaded < 0:
            return None
        try:
            Bitfield(rd.num_pieces, rd.bitfield)
        except ValueError:
            return None
        return rd


class FsResumeStore:
    """One ``.resume`` file per torrent, keyed by info hash, in ``root``."""

    def __init__(self, root: str | os.PathLike):
        self.root = os.fspath(root)

    def _path(self, info_hash: bytes) -> str:
        return os.path.join(self.root, f".{info_hash.hex()}.resume")

    def load(self, info_hash: bytes) -> ResumeData | None:
        try:
            with open(self._path(info_hash), "rb") as f:
                raw = f.read()
        except OSError:
            return None
        rd = ResumeData.decode(raw)
        if rd is None or rd.info_hash != info_hash:
            return None
        return rd

    def save(self, data: ResumeData) -> None:
        os.makedirs(self.root, exist_ok=True)
        tmp = self._path(data.info_hash) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data.encode())
        os.replace(tmp, self._path(data.info_hash))  # atomic checkpoint

    def delete(self, info_hash: bytes) -> None:
        try:
            os.remove(self._path(info_hash))
        except OSError:
            pass


class MemoryResumeStore:
    """In-memory store for tests."""

    def __init__(self):
        self.data: dict[bytes, bytes] = {}

    def load(self, info_hash: bytes) -> ResumeData | None:
        raw = self.data.get(info_hash)
        return ResumeData.decode(raw) if raw else None

    def save(self, data: ResumeData) -> None:
        self.data[data.info_hash] = data.encode()

    def delete(self, info_hash: bytes) -> None:
        self.data.pop(info_hash, None)
