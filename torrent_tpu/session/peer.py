"""Per-peer connection state (ref: peer.ts, 27 LoC — extended).

The reference tracks the four BitTorrent state flags in spec-default
position and a bitfield (peer.ts:17-25). A working leech/seed scheduler
additionally needs per-peer in-flight request tracking, transfer
accounting for the choke policy, and liveness timestamps.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from torrent_tpu.net.extension import ExtensionState
from torrent_tpu.utils.bitfield import Bitfield


@dataclass
class PeerConnection:
    peer_id: bytes
    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    num_pieces: int
    address: tuple[str, int] | None = None
    # BEP 10 negotiation state (net/extension.py); ``enabled`` is set from
    # the peer's handshake reserved bit 20.
    ext: ExtensionState = field(default_factory=ExtensionState)
    # BEP 6 fast extension, negotiated via reserved bit 0x04 of byte 7
    fast: bool = False
    # pieces we granted this peer (it may request them while we choke it)
    allowed_fast_out: set[int] = field(default_factory=set)
    # _fill_pipeline contention memo: True when the last full pick scan
    # could not fill this peer's budget; with a non-empty pipeline the
    # next scan is then deferred up to 50 ms (see the gate in
    # _fill_pipeline) instead of re-running per received block
    fill_starved: bool = False
    last_fill_at: float = 0.0
    # pieces the peer granted us (requestable while it chokes us)
    allowed_fast_in: set[int] = field(default_factory=set)
    # subset of ``inflight`` that was requested while choked (under an
    # allowed-fast grant); a reject of one of these withdraws the grant
    inflight_choked: set[tuple[int, int, int]] = field(default_factory=set)
    # consecutive RejectRequests with no block delivered in between; a
    # persistently-rejecting (yet unchoked) peer trips the snub gate via
    # this counter — the reject/re-request cycle itself keeps resetting
    # the wall-clock snub timer, so time alone can't catch it
    rejects_since_block: int = 0
    # currently waiting in the client-global download token bucket: the
    # peer IS delivering, it's just queued behind the cap — the snub
    # sweep must not read the queue latency as a stall
    pacing: bool = False
    # BEP 6 suggest-piece hints, most recent FIRST (newest hint wins)
    suggested: list[int] = field(default_factory=list)

    # BEP 3 spec-default flag positions (peer.ts:17-20)
    am_choking: bool = True
    am_interested: bool = False
    peer_choking: bool = True
    peer_interested: bool = False

    bitfield: Bitfield = None  # set in __post_init__
    # blocks we've requested from this peer and not yet received
    inflight: set[tuple[int, int, int]] = field(default_factory=set)
    # BEP 16 super-seeding (seed side): pieces we've revealed to this
    # peer via targeted Haves, and the subset not yet confirmed spread
    # (no OTHER peer has announced them back to us yet)
    ss_advertised: set[int] = field(default_factory=set)
    ss_unconfirmed: set[int] = field(default_factory=set)
    # peers that ever saw our REAL bitfield are exempt from the BEP 16
    # serve gate — hiding pieces we already told them about would just
    # stall their transfers (covers super-seed enabled mid-session and
    # the auto-flip when a super_seed-configured download completes)
    ss_exempt: bool = False

    bytes_down: int = 0  # payload received from peer
    bytes_up: int = 0  # payload sent to peer
    corrupt_pieces: int = 0  # pieces this peer helped fail verification
    # (time, bytes) marks anchoring the rate window. Initialized to the
    # REGISTRATION instant in __post_init__ — a (0.0, 0) default would
    # make the first window span the whole monotonic uptime, reporting a
    # near-zero rate for a peer that just delivered megabytes (the choke
    # policy would then mis-rank every fresh connection, and the swarm
    # telemetry would export the same lie)
    _rate_mark: tuple[float, int] = None  # (time, bytes_down) snapshot
    _up_mark: tuple[float, int] = None  # (time, bytes_up) snapshot
    # when each in-flight request was written (mirror of ``inflight``,
    # maintained at the same mutation sites): block round-trip times for
    # the swarm telemetry's RTT histograms
    req_sent_at: dict[tuple[int, int, int], float] = field(default_factory=dict)
    # memoized swarm-telemetry key (Torrent._obs_key): the per-message
    # accounting path must not rebuild the string per 16 KiB block
    obs_key: str | None = None

    last_rx: float = field(default_factory=time.monotonic)
    last_tx: float = field(default_factory=time.monotonic)
    # registration time: slot recycling must not evict a connection so
    # fresh it hasn't had a chance to express interest yet
    connected_at: float = field(default_factory=time.monotonic)
    # last time a *piece block* arrived (anti-snubbing; last_rx counts any
    # message, keepalives included, so it can't detect a data stall)
    last_block_rx: float = field(default_factory=time.monotonic)
    # stalled-while-owing-blocks: no fresh requests outside endgame until
    # this deadline passes or a block arrives (a permanent flag could
    # deadlock the whole session after a transient network stall)
    snubbed_until: float = 0.0
    # whether the peer connected to us (its address port is then an
    # ephemeral source port, NOT its listen port — PEX must not gossip it)
    inbound: bool = False
    # addresses already PEXed to this peer (BEP 11 sends deltas)
    pex_sent: set[tuple[str, int]] = field(default_factory=set)

    @property
    def snubbed(self) -> bool:
        return time.monotonic() < self.snubbed_until

    def __post_init__(self):
        if self.bitfield is None:
            self.bitfield = Bitfield(self.num_pieces)
        if self._rate_mark is None or self._up_mark is None:
            now = time.monotonic()
            self._rate_mark = (now, self.bytes_down)
            self._up_mark = (now, self.bytes_up)

    def dial_address(self) -> tuple[str, int] | None:
        """The address this peer can be dialed back on: its source IP plus
        the BEP 10 ``p`` listen port when advertised (an inbound peer's
        TCP source port is ephemeral, not its listener)."""
        if self.address is None:
            return None
        port = self.ext.listen_port or self.address[1]
        return (self.address[0], port)

    def download_rate(self) -> float:
        """Bytes/sec received since the last choke-policy snapshot."""
        t0, b0 = self._rate_mark
        dt = time.monotonic() - t0
        if dt <= 0:
            return 0.0
        return (self.bytes_down - b0) / dt

    def upload_rate(self) -> float:
        """Bytes/sec served since the last choke-policy snapshot."""
        t0, b0 = self._up_mark
        dt = time.monotonic() - t0
        if dt <= 0:
            return 0.0
        return (self.bytes_up - b0) / dt

    def snapshot_rate(self) -> None:
        now = time.monotonic()
        self._rate_mark = (now, self.bytes_down)
        self._up_mark = (now, self.bytes_up)

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass

    def __repr__(self) -> str:
        return f"PeerConnection({self.peer_id[:8]!r}, have={self.bitfield.count()}/{self.num_pieces})"
