"""Pure-v2 (BEP 52) swarm support: session-facing geometry adapter.

The session runtime (``session/torrent.py``) speaks one flat piece space:
``info.pieces[i]`` is the expected digest of piece ``i`` and bytes live at
``i * piece_length``. BitTorrent v2 replaces that with per-file SHA-256
merkle trees — so this module projects the v2 world into the flat space
the way BEP 52 itself does for the wire protocol:

- files are laid out in file-tree order, each starting on a piece
  boundary (v2 pieces NEVER span files — the gap after a file's last
  piece is virtual, never on disk and never on the wire);
- the expected digest of a piece is its merkle subtree root: the file's
  ``piece layers`` entry for multi-piece files, or the file's
  ``pieces root`` itself for files no larger than one piece;
- each piece carries its leaf-pad target (``piece_pad_leaves``): blocks
  per piece for multi-piece files, the file's own next-power-of-two
  block count for single-piece files (BEP 52's two padding rules).

``V2SessionMeta`` then duck-types ``codec.metainfo.Metainfo`` —
``info_hash`` is the truncated SHA-256 (what BEP 52 puts in the 68-byte
handshake and tracker announces; the v2 analogue of the reference's
``protocol.ts:36-67`` SHA-1 handshake), and ``raw`` keeps ``info`` +
``piece layers`` so the session can serve ut_metadata and BEP 52 hash
requests unchanged.

No reference counterpart — rclararey/torrent is v1-only; this is
beyond-parity surface completing the builder's own v2 plane.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from torrent_tpu.codec.metainfo import FileEntry
from torrent_tpu.codec.metainfo_v2 import BLOCK, InfoDictV2, MetainfoV2


class V2Error(ValueError):
    pass


@dataclass(frozen=True)
class V2SessionInfo:
    """InfoDict-compatible view of a v2 torrent (flat piece space)."""

    name: str
    piece_length: int
    pieces: tuple[bytes, ...]  # 32-byte expected merkle roots per piece
    length: int  # piece-space span: last file's aligned start + its length
    payload_length: int  # true byte total (sum of file lengths)
    files: tuple[FileEntry, ...] | None
    piece_sizes: tuple[int, ...]  # actual byte length of each piece
    piece_pad_leaves: tuple[int, ...]  # merkle leaf-pad target per piece

    # flags the generic layers key off (storage alignment, piece sizes,
    # 32-byte digests) — class-level so dataclass equality ignores them
    v2 = True
    piece_aligned = True

    @property
    def num_pieces(self) -> int:
        return len(self.pieces)

    @property
    def is_multi_file(self) -> bool:
        return self.files is not None


@dataclass(frozen=True)
class V2SessionMeta:
    """Metainfo-compatible wrapper carrying the v2 identities."""

    announce: str
    info: V2SessionInfo
    info_hash: bytes  # 20-byte TRUNCATED sha-256 (wire/registry key)
    info_hash_v2: bytes  # full 32-byte infohash
    meta_v2: MetainfoV2 | None = field(repr=False, default=None)
    raw: dict = field(repr=False, default_factory=dict)

    @property
    def web_seeds(self) -> tuple[str, ...]:
        """BEP 19 ``url-list``. v2's aligned piece space makes webseeds
        WORK with the generic per-segment fetcher: pieces never span
        files, piece sizes never reach into the alignment gaps, so every
        piece maps to exactly one ranged GET inside one file's URL."""
        from torrent_tpu.codec.metainfo import parse_url_list

        return parse_url_list(self.raw.get(b"url-list"))

    @property
    def http_seeds(self) -> tuple[str, ...]:
        """BEP 17 ``httpseeds`` (piece-keyed GETs) — same parsing as v1."""
        from torrent_tpu.codec.metainfo import parse_url_list

        return parse_url_list(self.raw.get(b"httpseeds"))

    @property
    def similar(self) -> tuple[bytes, ...]:
        """BEP 38 hints (the CLI writes them at the top level for v2)."""
        from torrent_tpu.codec.metainfo import parse_similar

        return parse_similar(self.raw)

    @property
    def collections(self) -> tuple[str, ...]:
        from torrent_tpu.codec.metainfo import parse_collections

        return parse_collections(self.raw)

    @property
    def update_url(self) -> str | None:
        """BEP 39 — so ``check_for_update`` works for v2 torrents too."""
        from torrent_tpu.codec.metainfo import parse_update_url

        return parse_update_url(self.raw)


def _pad_target(length: int) -> int:
    """Leaf-pad target for a file no larger than one piece: the next
    power of two of its OWN block count (BEP 52)."""
    nblocks = max(1, -(-length // BLOCK))
    return 1 << max(0, (nblocks - 1).bit_length())


def v2_session_info(
    info: InfoDictV2, piece_layers: dict[bytes, tuple[bytes, ...]]
) -> V2SessionInfo:
    """Flatten a v2 info dict + layers into session piece geometry."""
    plen = info.piece_length
    lpp = plen // BLOCK
    pieces: list[bytes] = []
    sizes: list[int] = []
    pads: list[int] = []
    entries: list[FileEntry] = []
    span_end = 0
    pos = 0  # aligned piece-space cursor
    for f in info.files:
        entries.append(FileEntry(length=f.length, path=f.path))
        if f.length == 0:
            continue
        n = -(-f.length // plen)
        if n == 1:
            pieces.append(f.pieces_root)
            sizes.append(f.length)
            pads.append(_pad_target(f.length))
        else:
            layer = piece_layers.get(f.pieces_root)
            if layer is None or len(layer) != n:
                raise V2Error(
                    f"file {'/'.join(f.path)}: piece layer missing or wrong length"
                )
            pieces.extend(layer)
            sizes.extend([plen] * (n - 1))
            sizes.append(f.length - (n - 1) * plen)
            pads.extend([lpp] * n)
        span_end = pos + f.length
        pos += n * plen
    single = len(entries) == 1 and entries[0].path == (info.name,)
    return V2SessionInfo(
        name=info.name,
        piece_length=plen,
        pieces=tuple(pieces),
        length=span_end,
        payload_length=info.length,
        files=None if single else tuple(entries),
        piece_sizes=tuple(sizes),
        piece_pad_leaves=tuple(pads),
    )


def v2_session_meta(meta: MetainfoV2) -> V2SessionMeta:
    """Session wrapper for a parsed v2 ``.torrent``."""
    return V2SessionMeta(
        announce=meta.announce or "",
        info=v2_session_info(meta.info, meta.piece_layers),
        info_hash=meta.truncated_info_hash,
        info_hash_v2=meta.info_hash_v2,
        meta_v2=meta,
        raw=meta.raw,
    )


def v2_session_meta_from_parts(
    info_bytes: bytes,
    info_hash_v2: bytes,
    piece_layers: dict[bytes, tuple[bytes, ...]],
    announce: str = "",
) -> V2SessionMeta:
    """Session wrapper from a magnet join: fetched info-dict bytes
    (already SHA-256-validated against the btmh topic) + hash-transfer
    piece layers (each already proven against its ``pieces root``)."""
    from torrent_tpu.codec.bencode import bdecode
    from torrent_tpu.codec.metainfo_v2 import parse_v2_info_dict

    decoded = bdecode(info_bytes, strict=False)
    parsed = parse_v2_info_dict(decoded if isinstance(decoded, dict) else None)
    if parsed is None:
        raise V2Error("fetched info dict is not a valid BEP 52 info dict")
    raw: dict = {b"info": decoded}
    if piece_layers:
        raw[b"piece layers"] = {r: b"".join(l) for r, l in piece_layers.items()}
    meta = MetainfoV2(
        announce=announce or None,
        info=parsed,
        info_hash_v2=info_hash_v2,
        piece_layers=dict(piece_layers),
        raw=raw,
    )
    return V2SessionMeta(
        announce=announce,
        info=v2_session_info(parsed, dict(piece_layers)),
        info_hash=info_hash_v2[:20],
        info_hash_v2=info_hash_v2,
        meta_v2=meta,
        raw=raw,
    )


def multi_piece_roots(info: InfoDictV2) -> list[tuple[bytes, int]]:
    """``(pieces_root, n_pieces)`` for every file larger than one piece —
    the set a magnet joiner must fetch piece layers for."""
    plen = info.piece_length
    out = []
    for f in info.files:
        if f.length > plen:
            out.append((f.pieces_root, -(-f.length // plen)))
    return out
