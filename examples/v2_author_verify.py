"""BitTorrent v2 (BEP 52): author a merkle-tree torrent and verify it.

Builds a v2 metainfo for a directory (per-file SHA-256 merkle trees,
16 KiB leaves), round-trips it through the codec, then verifies the
content against the piece layers — including pinpointing a corrupted
file. The same ``hasher="tpu"`` switch batches leaf hashing and tree
reduction onto the accelerator (the v2 plane sustains multi-GiB/s
on-device; see BASELINE.md).

Run:  python examples/v2_author_verify.py
"""

import os
import sys
import tempfile

try:
    import torrent_tpu  # noqa: F401  (installed)
except ModuleNotFoundError:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from torrent_tpu import parse_metainfo_v2
from torrent_tpu.codec.metainfo_v2 import encode_metainfo_v2
from torrent_tpu.models.v2 import build_v2, verify_v2


def main() -> None:
    with tempfile.TemporaryDirectory() as work:
        src = os.path.join(work, "corpus")
        os.makedirs(os.path.join(src, "nested"))
        rng = np.random.default_rng(11)
        paths = {}
        for rel in ("a.bin", os.path.join("nested", "b.bin")):
            p = os.path.join(src, rel)
            with open(p, "wb") as f:
                f.write(rng.integers(0, 256, 150_000, dtype=np.uint8).tobytes())
            paths[rel] = p

        files = [
            (tuple(rel.split(os.sep)), p) for rel, p in sorted(paths.items())
        ]
        meta = build_v2(files, name="corpus", piece_length=65536, hasher="cpu")
        data = encode_metainfo_v2(meta.info, meta.piece_layers)
        m = parse_metainfo_v2(data)
        print(
            f"authored v2: {m.info.name!r}, {len(m.info.files)} files, "
            f"infohash {m.info_hash_v2.hex()[:16]}…"
        )

        def read_file(path_tuple):
            p = os.path.join(src, *path_tuple)
            return p if os.path.exists(p) else None

        report = verify_v2(read_file, m, hasher="cpu")
        summary = {"/".join(f): bool(ok.all()) for f, ok in report.items()}
        print("clean verify:", summary)
        assert all(summary.values())

        with open(paths[os.path.join("nested", "b.bin")], "r+b") as f:
            f.seek(70_000)
            f.write(b"\x00" * 10)
        report = verify_v2(read_file, m, hasher="cpu")
        summary = {"/".join(f): bool(ok.all()) for f, ok in report.items()}
        print("after corruption:", summary)
        bad = ["/".join(f) for f, ok in report.items() if not ok.all()]
        assert bad == ["nested/b.bin"], bad
        bad_pieces = np.flatnonzero(~report[("nested", "b.bin")])
        print(f"corruption isolated to {bad[0]}, piece(s) {bad_pieces}")


if __name__ == "__main__":
    main()
