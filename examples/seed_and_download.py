"""End-to-end swarm on localhost: author, track, seed, download, verify.

Everything a reference user's first session does, as one runnable
program (the library analogue of `torrent-tpu make` + `seed` + `add`):

1. author a .torrent for a directory (``make_torrent``)
2. run a private HTTP tracker in-process (``server.run_tracker``)
3. seed the original directory with one client
4. download into a second directory with another client
5. byte-compare the result and print live session counters

Run:  python examples/seed_and_download.py   (pure CPU, ~seconds)
"""

import asyncio
import filecmp
import os
import sys
import tempfile

try:
    import torrent_tpu  # noqa: F401  (installed)
except ModuleNotFoundError:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torrent_tpu import Client, ClientConfig, FsStorage, Storage, parse_metainfo
from torrent_tpu.server import ServeOptions, run_tracker
from torrent_tpu.tools.make_torrent import make_torrent


async def main() -> None:
    with tempfile.TemporaryDirectory() as work:
        # --- a small content directory to share
        src = os.path.join(work, "album")
        os.makedirs(src)
        for i, size in enumerate((300_000, 120_000, 5_000)):
            with open(os.path.join(src, f"track{i}.flac"), "wb") as f:
                f.write(os.urandom(size))

        # --- tracker (ephemeral port, announce interval 2 s)
        server, pump = await run_tracker(
            ServeOptions(http_port=0, udp_port=None, host="127.0.0.1", interval=2)
        )
        announce = f"http://127.0.0.1:{server.http_port}/announce"

        # --- author; hasher="tpu" batches piece hashing on an accelerator
        meta_bytes = make_torrent(src, announce, piece_length=32768)
        m = parse_metainfo(meta_bytes)
        print(f"authored: {m.info.name!r}, {m.info.num_pieces} pieces")

        seeder = Client(ClientConfig(host="127.0.0.1"))
        leecher = Client(ClientConfig(host="127.0.0.1"))
        await seeder.start()
        await leecher.start()
        try:
            # seed: storage rooted at the directory CONTAINING the content
            t_seed = await seeder.add(m, Storage(FsStorage(work), m.info))
            print(f"seeder state after recheck: {t_seed.state.name}")

            dst = os.path.join(work, "downloads")
            os.makedirs(dst)
            t = await leecher.add(m, Storage(FsStorage(dst), m.info))
            await asyncio.wait_for(t.on_complete.wait(), timeout=60)
            print(
                f"downloaded {t.downloaded} bytes in "
                f"{t.status()['pieces']} pieces; state={t.state.name}"
            )

            match, mismatch, errors = filecmp.cmpfiles(
                src,
                os.path.join(dst, m.info.name),
                [f"track{i}.flac" for i in range(3)],
                shallow=False,
            )
            assert not mismatch and not errors, (mismatch, errors)
            print(f"byte-identical files: {match}")
        finally:
            await seeder.close()
            await leecher.close()
            server.close()
            await asyncio.wait_for(pump, 5)


if __name__ == "__main__":
    asyncio.run(main())
