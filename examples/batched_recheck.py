"""Batched piece verification — the TPU hash plane as a library call.

Authors a torrent for a generated directory, corrupts one byte, then
rechecks every piece with ``verify_pieces`` and reports exactly which
piece went bad. ``hasher="tpu"`` routes the same call through the
Pallas SHA-1 plane (35k+ pieces/s measured through a relay tunnel,
246k on-device — see BASELINE.md); ``hasher="cpu"`` keeps everything
host-side, which is what this demo uses so it runs anywhere.

Run:  python examples/batched_recheck.py            (CPU)
      python examples/batched_recheck.py tpu        (with an accelerator)
"""

import os
import sys
import tempfile

try:
    import torrent_tpu  # noqa: F401  (installed)
except ModuleNotFoundError:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from torrent_tpu import FsStorage, Storage, parse_metainfo, verify_pieces
from torrent_tpu.tools.make_torrent import make_torrent


def main() -> None:
    hasher = sys.argv[1] if len(sys.argv) > 1 else "cpu"
    with tempfile.TemporaryDirectory() as work:
        src = os.path.join(work, "dataset")
        os.makedirs(src)
        rng = np.random.default_rng(7)
        for name, size in (("shard0.bin", 800_000), ("shard1.bin", 450_000)):
            with open(os.path.join(src, name), "wb") as f:
                f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())

        meta = parse_metainfo(
            make_torrent(src, "http://tracker.invalid/announce", piece_length=65536)
        )
        storage = Storage(FsStorage(work), meta.info)

        ok = verify_pieces(storage, meta.info, hasher=hasher)
        print(f"clean recheck ({hasher}): {int(ok.sum())}/{len(ok)} pieces valid")

        # flip one byte in the middle of shard1 and recheck
        victim = os.path.join(src, "shard1.bin")
        with open(victim, "r+b") as f:
            f.seek(123_456)
            b = f.read(1)
            f.seek(123_456)
            f.write(bytes([b[0] ^ 0xFF]))

        ok = verify_pieces(storage, meta.info, hasher=hasher)
        bad = np.flatnonzero(~ok)
        print(f"after corruption: {int(ok.sum())}/{len(ok)} valid; bad pieces {bad}")
        assert len(bad) == 1, "exactly one 64 KiB piece spans the flipped byte"


if __name__ == "__main__":
    main()
